"""Slot-based kv-cache manager for continuous-batching decode.

Owns ONE fixed ``[slots, cache_len]`` decode cache (the flax 'cache'
collection tree built by ``generation.init_decode_cache``) and maps
requests onto free slots. The flash-decode live-window contract
(ops/pallas/decode_attention.py) is what makes slot reuse safe without
ever zeroing the buffers:

- each slot's attention window is ``[0, lengths[slot] + 1)`` — the
  per-row ``end`` the serving decode step derives from its write
  positions — so K/V rows a *previous* tenant left beyond the current
  length are never attended;
- a fresh tenant's prefill overwrites ``[0, prompt_len)`` and every
  decode tick overwrites position ``lengths[slot]`` *before* the window
  grows to include it, so stale rows are always replaced before they
  become visible.

The scalar ``cache_index`` leaves inside the tree are unused on this
path (per-slot progress lives in ``lengths``; the model receives explicit
``cache_positions`` instead) — see ``SelfAttention._update_cache``.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np

__all__ = ["SlotKVCacheManager", "scatter_slot"]


def scatter_slot(cache, prefill_cache, slot):
    """Write a 1-row prefill cache tree into row ``slot`` of the slot cache.

    Pure function (used inside the engine's jitted prefill, ``slot`` may be
    traced). K/V leaves carry a ``[..., batch, cache_len, heads, head_dim]``
    suffix — the batch axis sits at -4 for both the scan-stacked
    ``[layers, batch, ...]`` and the unrolled nested layouts — and are
    updated at that axis; rank-<4 leaves (the ``cache_index`` scalars) are
    left untouched, since per-slot progress is tracked by the manager."""

    def put(big, small):
        if big.ndim < 4:
            return big
        starts = (0,) * (big.ndim - 4) + (slot, 0, 0, 0)
        return jax.lax.dynamic_update_slice(big, small, starts)

    return jax.tree.map(put, cache, prefill_cache)


class SlotKVCacheManager:
    """Fixed-slot decode cache + slot bookkeeping (free list, tenants).

    ``cache`` is the live device tree; the engine routes it through its
    jitted prefill/decode functions and stores the result back here.
    ``lengths`` is the HOST mirror of per-slot live row counts (the device
    copy rides the engine's state dict) — kept for observability without a
    device sync."""

    def __init__(self, model, slots: int, cache_len: int):
        from fleetx_tpu.models.gpt.generation import init_decode_cache

        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        if (model.cfg.decode_cache_len or 0) != cache_len:
            raise ValueError(
                f"model.cfg.decode_cache_len ({model.cfg.decode_cache_len}) "
                f"must equal the manager's cache_len ({cache_len})"
            )
        self.slots = slots
        self.cache_len = cache_len
        self.cache = init_decode_cache(model, slots)
        self.lengths = np.zeros(slots, np.int64)
        self.request_ids: List[Optional[int]] = [None] * slots
        # lowest-index-first allocation keeps runs deterministic
        self._free = list(range(slots - 1, -1, -1))

    @property
    def free_count(self) -> int:
        """Number of slots available for admission."""
        return len(self._free)

    @property
    def active_count(self) -> int:
        """Number of slots currently holding a live request."""
        return self.slots - len(self._free)

    def occupancy(self) -> float:
        """Fraction of slots holding a live request."""
        return self.active_count / self.slots

    def alloc(self, request_id: int, prompt_len: int) -> Optional[int]:
        """Claim the lowest free slot for ``request_id`` (None when full)."""
        if not self._free:
            return None
        if prompt_len > self.cache_len:
            raise ValueError(
                f"prompt_len {prompt_len} exceeds cache_len {self.cache_len}"
            )
        slot = self._free.pop()
        self.request_ids[slot] = request_id
        self.lengths[slot] = prompt_len
        return slot

    def free(self, slot: int) -> None:
        """Release ``slot`` for the next queued request. No buffer zeroing:
        the live-window contract (module docstring) keeps stale rows
        invisible to the next tenant."""
        if self.request_ids[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        self.request_ids[slot] = None
        self.lengths[slot] = 0
        self._free.append(slot)
        self._free.sort(reverse=True)
