"""KV-cache managers for continuous-batching decode: paged + slot-based.

Two storage strategies behind one engine:

- :class:`PagedKVCacheManager` (the default): K/V live in ONE shared pool
  of ``[num_pages, page_size, heads, head_dim]`` pages; each request
  holds a block table mapping its logical positions to physical pages
  (vLLM-style). Cache capacity and prefill compute track tokens actually
  live, not per-slot worst case: a short request pins pages for ITS
  tokens only, and requests sharing a token prefix share the prefix's
  pages through a refcounted trie (:class:`PagePool`) — one prefill
  serves them all.
- :class:`SlotKVCacheManager` (compat, ``paged=False`` /
  ``FLEETX_SERVING_PAGED=0``): the original fixed ``[slots, cache_len]``
  cache, one full-length lane per request.

Both rely on the flash-decode live-window contract
(ops/pallas/decode_attention.py) to skip ALL buffer zeroing:

- each row's attention window is ``[0, lengths[row] + 1)`` — the per-row
  ``end`` the serving decode step derives from its write positions — so
  K/V a *previous* tenant left beyond the current length (or in a
  recycled page) is never attended;
- a fresh tenant's prefill overwrites its window's positions and every
  decode tick overwrites position ``lengths[row]`` *before* the window
  grows to include it, so stale rows are always replaced before they
  become visible.

The paged pool reserves physical page 0 as the TRASH page: zeroed block-
table entries (freed lanes, logical pages not yet allocated) route the
engine's pinned/tail writes there, so no write can land in a page owned
by someone else. Copy-on-write degenerates to an invariant instead of a
copy: only FULL prompt pages are ever shared (registered in the trie),
writes only target positions >= the shared prefix length, and those
positions live in freshly-allocated refcount-1 pages — a shared page is
structurally read-only.

The scalar ``cache_index`` leaves inside the cache tree are unused on
the serving path (per-row progress lives in ``lengths``; the model
receives explicit ``cache_positions`` instead) — see
``SelfAttention._update_cache``.

Quantized storage (``decode_kv_dtype="int8"`` on the model config, wired
by ``FLEETX_SERVING_KV_DTYPE``; docs/QUANTIZATION.md): the cache tree
built by ``init_decode_cache`` then carries int8 K/V leaves plus fp32
``cached_key_scale``/``cached_value_scale`` leaves of per-vector scales.
Nothing in this module special-cases them — the scale leaves share the
K/V leaves' trailing-rank layout (``[..., lanes|pages, len, heads, 1]``),
so :func:`scatter_slot` slots them by the same rank-≥4 rule and the page
lifecycle (trash-page routing, no-zeroing, refcounts) is dtype-blind:
a page's scales travel with its values because both are indexed by the
same block table. :meth:`_LaneBook.cache_nbytes` measures the actual
device bytes either way, which is how the ~2× HBM win is asserted.

Two-level page cache (``FLEETX_SERVING_HOST_CACHE_BYTES``;
docs/SERVING.md): with a :class:`HostPageStore` attached, LRU eviction
of a zero-ref warm trie subtree SPILLS each page's content (K/V and, at
int8, the scale pages — every cache leaf) to bounded host DRAM instead
of destroying it. Entries are keyed by the page's full token-chunk path
from the trie root, so they are content-addressed: a later prompt
carrying the same prefix revives them into fresh physical pages via one
batched device transfer per cache leaf, an engine ``recover()`` that
rebuilds the pool from scratch still matches them (the engine re-threads
the same store), and a stale entry can never be wrong — deterministic
prefill means identical tokens produce identical K/V. The pool stays
pure-host: the actual device reads/writes go through ``spill_fn`` /
``revive_fn`` callbacks the :class:`PagedKVCacheManager` binds (tests
drive the pool with dummy payloads, no backend needed).
"""

from __future__ import annotations

import hashlib
import heapq
import math
import os
import struct
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

__all__ = ["DiskPageStore", "HostPageStore", "PagePool",
           "PagedKVCacheManager", "SlotKVCacheManager", "TieredPageStore",
           "leaf_device_nbytes", "scatter_slot"]


def leaf_device_nbytes(leaf) -> int:
    """PER-DEVICE bytes of one array: the addressable shard's size, not
    the global one. Under a mesh-sharded serving engine the KV cache
    leaves split their heads axis over ``mp``, so the bytes a device
    actually holds — the number HBM capacity planning cares about — is
    the shard, and on a single device the shard IS the array."""
    shape = tuple(getattr(leaf, "shape", ()))
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(sharding, "shard_shape"):
        try:
            shape = sharding.shard_shape(shape)
        except Exception:  # abstract/tracer leaves: fall back to global
            pass
    return int(math.prod(shape)) * np.dtype(leaf.dtype).itemsize


class HostPageStore:
    """Bounded host-DRAM spill tier for KV pages (module docstring).

    A byte-budgeted LRU dict: ``key`` is a page's full token-chunk path
    (tuple of full-page token tuples from the trie root) and the payload
    is whatever the spilling manager handed over (per-leaf host arrays).
    Keys are content-addressed, so the store outlives any one
    :class:`PagePool`/:class:`PagedKVCacheManager` — the engine owns the
    store and re-threads it through ``recover()``'s rebuilt manager.
    Capacity pressure drops the OLDEST entries (counted in
    ``evicted_pages``); a payload larger than the whole budget is
    rejected outright. Pure host state, no locking (the serving engine
    is single-threaded per replica)."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 0:
            raise ValueError(
                f"capacity_bytes must be >= 0, got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self._entries: Dict[tuple, Tuple[object, int]] = {}  # insertion=LRU
        self.nbytes = 0
        self.spilled_pages = 0  # lifetime puts accepted
        self.revived_pages = 0  # lifetime pops on a prefix match
        self.evicted_pages = 0  # lifetime drops (capacity pressure)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def put(self, key, payload, nbytes: int) -> bool:
        """Insert one spilled page, evicting oldest entries until it
        fits; False (nothing stored) when ``nbytes`` exceeds the whole
        budget. Re-putting a key refreshes its payload and LRU slot."""
        if nbytes > self.capacity_bytes:
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self.nbytes -= old[1]
        while self.nbytes + nbytes > self.capacity_bytes and self._entries:
            k = next(iter(self._entries))
            self.nbytes -= self._entries.pop(k)[1]
            self.evicted_pages += 1
        self._entries[key] = (payload, nbytes)
        self.nbytes += nbytes
        self.spilled_pages += 1
        return True

    def get(self, key):
        """A matched page's payload for revival, refreshing its LRU
        slot. The entry STAYS — the tier is inclusive: the device gets a
        copy, and a fault that destroys the device copy (rollback,
        recovery, re-eviction) can revive this entry again. A later
        re-spill of the same path overwrites it with identical bytes
        (content-addressed keys cannot go stale). KeyError if absent."""
        payload, nbytes = self._entries.pop(key)
        self._entries[key] = (payload, nbytes)  # re-insert = LRU refresh
        self.revived_pages += 1
        return payload

    def pop(self, key):
        """Remove and return an entry's payload (explicit invalidation;
        the revive path uses :meth:`get`). KeyError if absent."""
        payload, nbytes = self._entries.pop(key)
        self.nbytes -= nbytes
        return payload

    def check_invariants(self) -> None:
        """Byte accounting must match the entries exactly and respect
        the budget (called from :meth:`PagePool.check_invariants`)."""
        want = sum(nb for _, nb in self._entries.values())
        assert self.nbytes == want, (
            f"host store nbytes {self.nbytes} != sum of entries {want}")
        assert self.nbytes <= self.capacity_bytes, (
            f"host store over budget: {self.nbytes} > {self.capacity_bytes}")

    # ------------------------------------------------- payload wire format
    # One spilled page's payload is a per-cache-leaf list of host arrays
    # (K page, V page, int8 scale pages when quantized) with None holding
    # the slots of rank-<4 leaves (the cache_index scalars that never
    # spill). to_bytes/from_bytes give that payload a PICKLE-FREE,
    # byte-exact wire form — the page-ship primitive the disaggregated
    # prefill/decode split and the shared DiskPageStore serialize over
    # (docs/SERVING.md "Disaggregated prefill/decode"), with none of
    # pickle's arbitrary-code-execution surface on the receiving replica.
    # Layout (little-endian): magic "FXPG" + u16 version + u16 entry
    # count, then per entry a none/array flag and, for arrays, dtype
    # string + shape + raw C-order bytes; a crc32 of everything before it
    # trails the whole blob (v2 — a page shipped across processes or read
    # back off disk must fail loudly on any bit flip, never revive
    # garbage K/V into a live cache).

    _MAGIC = b"FXPG"
    _VERSION = 2  # v2 = v1 + crc32 trailer; v1 blobs are rejected

    @staticmethod
    def payload_to_bytes(payload) -> bytes:
        """Serialize one spill payload (list of ``Optional[np.ndarray]``)
        to the wire format above. Byte-exact: dtypes (int8 values, fp32
        scales, bf16 via its numpy extension name) and shapes round-trip
        losslessly through :meth:`payload_from_bytes`."""
        out = [HostPageStore._MAGIC,
               struct.pack("<HH", HostPageStore._VERSION, len(payload))]
        for arr in payload:
            if arr is None:
                out.append(b"\x00")
                continue
            a = np.ascontiguousarray(arr)
            if a.dtype.names is not None or a.dtype.hasobject:
                raise ValueError(
                    f"payload leaf dtype {a.dtype} is not a plain array "
                    "dtype; only numeric cache leaves spill")
            # dtype.name, not dtype.str: the extension dtypes (bfloat16)
            # stringify as opaque void types under .str but round-trip
            # through np.dtype(name) once ml_dtypes is registered (jax
            # imports it)
            name = a.dtype.name.encode("ascii")
            out.append(b"\x01")
            out.append(struct.pack("<B", len(name)))
            out.append(name)
            out.append(struct.pack("<B", a.ndim))
            out.append(struct.pack(f"<{a.ndim}I", *a.shape))
            raw = a.tobytes()
            out.append(struct.pack("<Q", len(raw)))
            out.append(raw)
        body = b"".join(out)
        return body + struct.pack("<I", zlib.crc32(body))

    @staticmethod
    def payload_from_bytes(buf: bytes) -> list:
        """Inverse of :meth:`payload_to_bytes` (malformed/truncated/
        corrupted input raises ValueError — a corrupt shipped page must
        fail loudly, not revive garbage K/V). The crc32 trailer is
        verified BEFORE any entry is parsed, and pre-crc v1 blobs are
        rejected by version with an explicit error."""
        view = memoryview(buf)
        if bytes(view[:4]) != HostPageStore._MAGIC:
            raise ValueError("not a HostPageStore payload (bad magic)")
        if len(buf) < 12:  # magic + header + crc32 trailer
            raise ValueError(
                f"truncated payload: {len(buf)} bytes is shorter than the "
                "8-byte header + 4-byte crc32 trailer")
        version, count = struct.unpack("<HH", view[4:8])
        if version != HostPageStore._VERSION:
            raise ValueError(
                f"unsupported payload version {version}: this build "
                f"writes/reads v{HostPageStore._VERSION} (crc32-trailed); "
                "v1 predates the checksum — re-spill the page with a "
                "current build")
        (want_crc,) = struct.unpack("<I", view[-4:])
        got_crc = zlib.crc32(view[:-4])
        if got_crc != want_crc:
            raise ValueError(
                f"payload crc32 mismatch (stored {want_crc:#010x}, "
                f"computed {got_crc:#010x}): the page was corrupted in "
                "flight or at rest")
        end = len(buf) - 4
        pos, out = 8, []
        try:
            for _ in range(count):
                flag = view[pos]
                pos += 1
                if flag == 0:
                    out.append(None)
                    continue
                nlen = view[pos]
                pos += 1
                dtype = np.dtype(bytes(view[pos:pos + nlen]).decode("ascii"))
                pos += nlen
                ndim = view[pos]
                pos += 1
                shape = struct.unpack(f"<{ndim}I",
                                      view[pos:pos + 4 * ndim])
                pos += 4 * ndim
                (nbytes,) = struct.unpack("<Q", view[pos:pos + 8])
                pos += 8
                arr = np.frombuffer(
                    view[pos:pos + nbytes], dtype=dtype).reshape(shape)
                pos += nbytes
                out.append(arr.copy())  # own the memory, not the buffer
        except (struct.error, ValueError, IndexError, TypeError) as e:
            # IndexError: memoryview read past a truncation point;
            # TypeError: np.dtype() on a truncated dtype name — both are
            # the same "corrupt payload" condition the contract promises
            # to surface as ValueError (the crc check above catches
            # virtually all of these first; this is defense in depth
            # against a collision)
            raise ValueError(f"truncated/corrupt payload: {e}") from None
        if pos != end:
            raise ValueError(
                f"payload has {end - pos} trailing bytes before the crc")
        return out


class DiskPageStore:
    """Content-addressed, byte-bounded KV page store on shared disk —
    the cluster tier of the page cache (``FLEETX_SERVING_DISK_CACHE_DIR``
    / ``_BYTES``; docs/SERVING.md "Disaggregated prefill/decode").

    Same ``put``/``get``/``pop``/``in`` surface as :class:`HostPageStore`
    so :class:`PagePool` drives either (or both, via
    :class:`TieredPageStore`) without caring, but entries live as files
    under one directory EVERY replica in the fleet points at: a hot
    system prompt prefilled by any one replica is revivable by all of
    them, sustaining prefix hit rate past any single replica's host-DRAM
    budget. Filenames are the sha256 of the page's full token-chunk path
    (content-addressed — identical tokens produce identical K/V, so a
    file written by replica A is correct for replica B by construction),
    contents are the crc32-trailed :meth:`HostPageStore.payload_to_bytes`
    wire format (a corrupted file fails loudly at decode, never revives
    garbage). Writes are atomic (tmp + rename) so a reader never sees a
    half-written page; eviction is LRU by mtime over a directory scan,
    which stays coherent when several replica processes share the dir
    (``get`` touches the file). Capacity accounting is by actual file
    bytes — the serialized page, not the host-array footprint."""

    _SUFFIX = ".fxpg"

    def __init__(self, cache_dir: str, capacity_bytes: int):
        if capacity_bytes < 0:
            raise ValueError(
                f"capacity_bytes must be >= 0, got {capacity_bytes}")
        if not cache_dir:
            raise ValueError("cache_dir must be a non-empty path")
        self.cache_dir = str(cache_dir)
        self.capacity_bytes = int(capacity_bytes)
        os.makedirs(self.cache_dir, exist_ok=True)
        self.spilled_pages = 0  # lifetime puts accepted (this instance)
        self.revived_pages = 0  # lifetime gets served
        self.evicted_pages = 0  # lifetime files dropped under the budget
        self.hits = 0           # gets served (alias kept for the gauge)
        self.misses = 0         # membership probes that found nothing

    # ----------------------------------------------------------- addressing
    def _path(self, key) -> str:
        """File path for a token-chunk-path key: sha256 over the chunks
        (chunk boundaries separated so ``((1,2),)`` and ``((1,),(2,))``
        cannot collide), hex digest as the filename."""
        h = hashlib.sha256()
        for chunk in key:
            h.update(np.asarray(chunk, np.int64).tobytes())
            h.update(b"/")
        return os.path.join(self.cache_dir, h.hexdigest() + self._SUFFIX)

    def _files(self):
        """(path, stat) for every store file, oldest-mtime first.
        Concurrently vanished files (a sibling replica evicted them) are
        skipped — the scan must tolerate sharing."""
        out = []
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            return []
        for name in names:
            if not name.endswith(self._SUFFIX):
                continue
            path = os.path.join(self.cache_dir, name)
            try:
                out.append((path, os.stat(path)))
            except OSError:
                continue
        out.sort(key=lambda ps: (ps[1].st_mtime, ps[0]))
        return out

    def __len__(self) -> int:
        return len(self._files())

    @property
    def nbytes(self) -> int:
        """Bytes currently resident (actual file sizes — shared-dir
        coherent: siblings' writes count too)."""
        return sum(st.st_size for _, st in self._files())

    def __contains__(self, key) -> bool:
        if os.path.exists(self._path(key)):
            return True
        self.misses += 1
        return False

    def put(self, key, payload, nbytes: int = 0) -> bool:
        """Serialize + store one page under its content address,
        evicting oldest files until the budget holds; False (nothing
        stored) when the serialized page alone exceeds it. ``nbytes``
        (the host-array footprint the pool computed) is advisory here —
        disk accounting uses the wire bytes actually written."""
        del nbytes  # accounted from the serialized blob below
        blob = HostPageStore.payload_to_bytes(payload)
        if len(blob) > self.capacity_bytes:
            return False
        path = self._path(key)
        tmp = path + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)  # atomic: readers see old bytes or new
        except OSError:
            # full or read-only shared dir: the disk tier degrades to
            # nothing-stored, it must never fault the serving tick
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        self.spilled_pages += 1
        total = self.nbytes
        if total > self.capacity_bytes:
            for victim, st in self._files():
                if victim == path:
                    continue  # never evict the page just written
                try:
                    os.remove(victim)
                except OSError:
                    continue
                self.evicted_pages += 1
                total -= st.st_size
                if total <= self.capacity_bytes:
                    break
        return True

    def get(self, key):
        """Decode a stored page back to its host-array payload,
        refreshing its LRU slot (mtime touch — visible to every replica
        sharing the dir). KeyError when absent; ValueError when the file
        is corrupt (crc/format — the caller must treat that as a miss
        that fails loudly, not revive it)."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            raise KeyError(key) from None
        try:
            payload = HostPageStore.payload_from_bytes(blob)
        except ValueError:
            # self-heal: a corrupt entry must not outlive its first read,
            # or every prompt matching this prefix would re-hit it
            try:
                os.remove(path)
            except OSError:
                pass
            self.misses += 1  # a corrupt file reads as a (loud) miss
            raise
        try:
            os.utime(path)
        except OSError:
            pass  # a sibling evicted it mid-read; the payload is ours
        self.revived_pages += 1
        self.hits += 1
        return payload

    def pop(self, key):
        """Remove and return an entry's payload (explicit invalidation).
        KeyError if absent — or corrupt: ``get`` unlinks the bad file,
        so either way no entry remains afterwards."""
        try:
            payload = self.get(key)
        except ValueError:
            raise KeyError(key) from None
        self.hits -= 1  # a pop is not a cache hit
        self.revived_pages -= 1
        try:
            os.remove(self._path(key))
        except OSError:
            pass
        return payload

    def check_invariants(self) -> None:
        """Resident bytes must respect the budget. Tolerates transient
        overshoot only from files a SIBLING process wrote after this
        instance's last eviction pass — within one process the budget is
        re-enforced on every put."""
        total = self.nbytes
        assert total <= self.capacity_bytes or len(self._files()) <= 1, (
            f"disk store over budget: {total} > {self.capacity_bytes}")


class TieredPageStore:
    """Host-DRAM tier over a shared disk tier, behind the one store
    surface :class:`PagePool` drives (docs/SERVING.md "Disaggregated
    prefill/decode"): puts write through to both (the local replica keeps
    DRAM-speed revives, the fleet gets the page), gets serve host-first
    and fall back to disk — promoting a disk hit back into the host tier
    so a hot cross-replica prefix pays the file read once. The
    host-facing counters/properties delegate to the host tier (so
    ``ServingMetrics.observe_host_tier`` reads a tiered store unchanged);
    disk counters are scraped off ``.disk`` via ``observe_disk_tier``."""

    def __init__(self, host: HostPageStore, disk: DiskPageStore):
        self.host = host
        self.disk = disk

    def __len__(self) -> int:
        return len(self.host)

    @property
    def nbytes(self) -> int:
        return self.host.nbytes

    @property
    def capacity_bytes(self) -> int:
        return self.host.capacity_bytes

    @property
    def spilled_pages(self) -> int:
        return self.host.spilled_pages

    @property
    def revived_pages(self) -> int:
        return self.host.revived_pages

    @property
    def evicted_pages(self) -> int:
        return self.host.evicted_pages

    def __contains__(self, key) -> bool:
        return key in self.host or key in self.disk

    def put(self, key, payload, nbytes: int) -> bool:
        """Write-through: True when either tier kept the page."""
        kept_host = self.host.put(key, payload, nbytes)
        kept_disk = self.disk.put(key, payload, nbytes)
        return kept_host or kept_disk

    def get(self, key):
        """Host tier first; a disk hit is promoted into the host tier
        (counted as a host spill, like any other insertion)."""
        try:
            return self.host.get(key)
        except KeyError:
            pass
        payload = self.disk.get(key)
        nbytes = sum(a.nbytes for a in payload if a is not None)
        self.host.put(key, payload, nbytes)
        return payload

    def pop(self, key):
        """Invalidate in both tiers; payload from whichever had it."""
        payload = None
        try:
            payload = self.host.pop(key)
        except KeyError:
            pass
        try:
            disk_payload = self.disk.pop(key)
            payload = payload if payload is not None else disk_payload
        except KeyError:
            pass
        if payload is None:
            raise KeyError(key)
        return payload

    def check_invariants(self) -> None:
        self.host.check_invariants()
        self.disk.check_invariants()


def scatter_slot(cache, prefill_cache, slot):
    """Write a 1-row prefill cache tree into row ``slot`` of the slot cache.

    Pure function (used inside the engine's jitted prefill, ``slot`` may be
    traced). K/V leaves carry a ``[..., batch, cache_len, heads, head_dim]``
    suffix — the batch axis sits at -4 for both the scan-stacked
    ``[layers, batch, ...]`` and the unrolled nested layouts — and are
    updated at that axis; rank-<4 leaves (the ``cache_index`` scalars) are
    left untouched, since per-slot progress is tracked by the manager."""

    def put(big, small):
        if big.ndim < 4:
            return big
        starts = (0,) * (big.ndim - 4) + (slot, 0, 0, 0)
        return jax.lax.dynamic_update_slice(big, small, starts)

    return jax.tree.map(put, cache, prefill_cache)


class _LaneBook:
    """Decode-lane bookkeeping shared by both cache managers: a min-heap
    free list (lowest lane first, deterministic, O(log n) alloc/free —
    the original list re-sorted on every release), per-lane request ids,
    and the HOST mirror of per-lane live lengths (the device copy rides
    the engine's state dict) — kept for observability without a device
    sync."""

    def _init_lanes(self, slots: int) -> None:
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        self.slots = slots
        self.lengths = np.zeros(slots, np.int64)
        self.request_ids: List[Optional[int]] = [None] * slots
        self._free: List[int] = list(range(slots))

    @property
    def free_count(self) -> int:
        """Number of decode lanes available for admission."""
        return len(self._free)

    @property
    def active_count(self) -> int:
        """Number of decode lanes currently holding a live request."""
        return self.slots - len(self._free)

    def occupancy(self) -> float:
        """Fraction of decode lanes holding a live request."""
        return self.active_count / self.slots

    def _claim_lane(self, request_id: int, length: int) -> int:
        lane = heapq.heappop(self._free)
        self.request_ids[lane] = request_id
        self.lengths[lane] = length
        return lane

    def _release_lane(self, slot: int) -> None:
        if self.request_ids[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        self.request_ids[slot] = None
        self.lengths[slot] = 0
        heapq.heappush(self._free, slot)

    def cache_nbytes(self) -> int:
        """PER-DEVICE bytes of the live cache tree, measured from the
        actual leaves (int8 values + fp32 scales when kv-quantized,
        full-width K/V otherwise; the addressable shard when the engine
        sharded the heads over a mesh) — the scrapeable ground truth for
        the quantized ~½× AND the mesh ÷mp HBM stories
        (``fleetx_serving_kv_cache_bytes``)."""
        return sum(leaf_device_nbytes(leaf)
                   for leaf in jax.tree.leaves(self.cache))


class SlotKVCacheManager(_LaneBook):
    """Fixed-slot decode cache + slot bookkeeping (free list, tenants).

    ``cache`` is the live device tree; the engine routes it through its
    jitted prefill/decode functions and stores the result back here."""

    def __init__(self, model, slots: int, cache_len: int):
        from fleetx_tpu.models.gpt.generation import init_decode_cache

        if (model.cfg.decode_cache_len or 0) != cache_len:
            raise ValueError(
                f"model.cfg.decode_cache_len ({model.cfg.decode_cache_len}) "
                f"must equal the manager's cache_len ({cache_len})"
            )
        self._init_lanes(slots)
        self.cache_len = cache_len
        self.cache = init_decode_cache(model, slots)

    def alloc(self, request_id: int, prompt_len: int) -> Optional[int]:
        """Claim the lowest free slot for ``request_id`` (None when full)."""
        if not self._free:
            return None
        if prompt_len > self.cache_len:
            raise ValueError(
                f"prompt_len {prompt_len} exceeds cache_len {self.cache_len}"
            )
        return self._claim_lane(request_id, prompt_len)

    def free(self, slot: int) -> None:
        """Release ``slot`` for the next queued request. No buffer zeroing:
        the live-window contract (module docstring) keeps stale rows
        invisible to the next tenant."""
        self._release_lane(slot)


class _TrieNode:
    """One full page of prompt tokens in the prefix trie: ``key`` is the
    page's token tuple, ``page`` its physical index; children extend the
    prefix by one more full page. The node path from the root IS the
    prefix hash — dict lookups chunk by chunk, no rolling hash to
    collide."""

    __slots__ = ("key", "page", "parent", "children")

    def __init__(self, key, page: int, parent: "_TrieNode" = None):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[tuple, "_TrieNode"] = {}


class PagePool:
    """Host-side page allocator + refcounted prefix trie (PURE host state
    — no device arrays, so allocator/trie invariants are unit-testable
    without a model or backend).

    Physical page 0 is the reserved TRASH page (module docstring): it is
    born with a permanent refcount, never enters the free stack, and
    absorbs every write routed through a zeroed block-table entry.

    Lifecycle of a shareable page: a full prompt page is prefilled into a
    refcount-1 page, registered in the trie (``register_prefix``), and
    from then on other lanes' ``alloc`` calls can match it (refcount++).
    When its last holder frees, the page parks in ``_cached`` — content
    intact, trie node alive — where a later match revives it for free or
    LRU eviction reclaims it (evicting a node evicts its whole subtree:
    children's refcounts can never exceed their parent's, so a refcount-0
    parent guarantees refcount-0 children and nothing live is stranded).

    Alloc/free cost: O(pages touched) with an O(1) free-stack — no sort,
    no scan of the pool."""

    def __init__(self, num_pages: int, page_size: int, lanes: int,
                 lane_pages: int, prefix_cache: bool = True,
                 host_store: Optional[HostPageStore] = None,
                 spill_fn: Optional[Callable] = None,
                 revive_fn: Optional[Callable] = None):
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if num_pages < lane_pages + 1:
            raise ValueError(
                f"num_pages {num_pages} cannot hold one full lane "
                f"({lane_pages} pages) plus the trash page")
        self.num_pages = num_pages
        self.page_size = page_size
        self.lanes = lanes
        self.lane_pages = lane_pages
        self.prefix_cache = prefix_cache
        # block tables: 0 = trash page = "not allocated"
        self.tables = np.zeros((lanes, lane_pages), np.int32)
        self.alloc_counts = np.zeros(lanes, np.int64)
        self.shared_counts = np.zeros(lanes, np.int64)
        self.ref = np.zeros(num_pages, np.int64)
        self.ref[0] = 1  # trash page: permanently pinned
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._root = _TrieNode(None, 0, None)
        self._node_of_page: Dict[int, _TrieNode] = {}
        # refcount-0 pages still registered in the trie, insertion order =
        # LRU (dicts preserve it; moves re-insert)
        self._cached: Dict[int, _TrieNode] = {}
        # bumped on every block-table change so the engine re-uploads the
        # device copy only when something moved
        self.version = 0
        # host spill tier (module docstring): active only when all three
        # pieces are present AND the trie is on (spilled entries are
        # matched by token-chunk path — without the trie nothing could
        # ever revive them)
        self.host_store = (host_store if prefix_cache and spill_fn
                           and revive_fn else None)
        self._spill_fn = spill_fn
        self._revive_fn = revive_fn

    # ------------------------------------------------------------- stats

    @property
    def usable_pages(self) -> int:
        """Pages available to requests (the pool minus the trash page)."""
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        """Pages obtainable right now: the free stack plus refcount-0
        cached pages (reclaimable by LRU eviction)."""
        return len(self._free) + len(self._cached)

    @property
    def pages_in_use(self) -> int:
        """Pages pinned by at least one live lane."""
        return self.usable_pages - self.free_pages

    @property
    def cached_pages(self) -> int:
        """Refcount-0 pages kept warm in the trie (reclaimable)."""
        return len(self._cached)

    def occupancy(self) -> float:
        """Fraction of usable pages pinned by live lanes."""
        return self.pages_in_use / max(self.usable_pages, 1)

    # ------------------------------------------------------------ helpers

    def _chunks(self, tokens) -> List[tuple]:
        """Full-page token tuples of a prompt, capped so at least the last
        prompt token is always re-prefilled (its logits seed the first
        sampled token — a 100% trie hit would leave nothing to run)."""
        n = (len(tokens) - 1) // self.page_size
        return [tuple(int(t) for t in
                      tokens[i * self.page_size:(i + 1) * self.page_size])
                for i in range(n)]

    def _match_path(self, chunks) -> List[_TrieNode]:
        path, node = [], self._root
        for c in chunks:
            node = node.children.get(c)
            if node is None:
                break
            path.append(node)
        return path

    def _take_page(self) -> Optional[int]:
        """Pop a free page; when the stack is dry, evict the LRU cached
        prefix subtree (all refcount-0 by the parent>=child invariant) —
        spilling its pages to the host tier first when one is attached."""
        if not self._free:
            if not self._cached:
                return None
            node = next(iter(self._cached.values()))  # oldest zero-ref
            self._evict_subtree(node)
        return self._free.pop()

    @staticmethod
    def _node_key(node: _TrieNode) -> tuple:
        """A node's full token-chunk path from the root — the content
        address its spilled payload is stored under."""
        parts = []
        while node is not None and node.key is not None:
            parts.append(node.key)
            node = node.parent
        return tuple(reversed(parts))

    def _evict_subtree(self, node: _TrieNode) -> None:
        """Reclaim a zero-ref cached subtree's physical pages. With a
        host tier attached, each page's content is spilled (ONE batched
        device read for the whole subtree) before the page frees; the
        warm data then survives as host entries revivable by token path.
        Without one, this is plain destruction (the pre-spill behavior).
        """
        if node.parent is not None:
            del node.parent.children[node.key]
        victims: List[_TrieNode] = []
        stack = [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            victims.append(n)
        if self.host_store is not None and victims:
            keys = [self._node_key(n) for n in victims]
            for (payload, nbytes), key in zip(
                    self._spill_fn([n.page for n in victims]), keys):
                self.host_store.put(key, payload, nbytes)
        for n in victims:
            self._cached.pop(n.page, None)
            del self._node_of_page[n.page]
            self._free.append(n.page)
            n.children = {}
            n.parent = None

    def _match_host(self, chunks: List[tuple],
                    path: List[_TrieNode]) -> List[tuple]:
        """Continue a trie prefix match into the host spill tier: the
        chunk paths extending ``path`` that have spilled payloads. Stops
        at the first miss (a revived page is only attendable if every
        page before it is present too)."""
        if self.host_store is None:
            return []
        key = self._node_key(path[-1]) if path else ()
        out = []
        for c in chunks[len(path):]:
            key = key + (c,)
            if key not in self.host_store:
                break
            out.append(key)
        return out

    # ----------------------------------------------------------- requests

    def pages_needed(self, tokens) -> int:
        """Pages an ``alloc`` of this prompt would draw from the
        free/reclaimable pool: fresh pages covering the non-shared part
        of ``[0, prompt_len]`` (the +1 slot is the first sampled token's
        write position), PLUS matched prefix pages currently parked in
        the warm cache — revival moves those out of the reclaimable
        count, so they cost pool capacity exactly like a fresh claim."""
        chunks = self._chunks(tokens) if self.prefix_cache else []
        path = self._match_path(chunks)
        fresh = len(tokens) // self.page_size + 1 - len(path)
        revived = sum(1 for n in path if self.ref[n.page] == 0)
        return fresh + revived

    def can_admit(self, tokens) -> bool:
        """Page-granular admission check: True iff ``alloc`` would
        succeed right now (exact — kept in lockstep with ``alloc``'s own
        availability accounting, so the engine can pop-then-alloc)."""
        return self.pages_needed(tokens) <= self.free_pages

    def alloc(self, lane: int, tokens) -> Optional[int]:
        """Build ``lane``'s block table for prompt ``tokens``: shared
        prefix pages from the trie (refcount++), host-spilled prefix
        pages revived into fresh physical pages (one batched device
        write), plus fresh refcount-1 pages covering the rest of
        ``[0, prompt_len]``. Returns the shared prefix length in TOKENS —
        trie-shared AND host-revived pages both skip their prefill — or
        None, with no state committed, when the pool cannot supply the
        physical pages (host revivals draw from the same free pool as
        fresh claims, so :meth:`pages_needed` already counts them)."""
        if self.alloc_counts[lane]:
            raise ValueError(f"lane {lane} already holds pages")
        need_total = len(tokens) // self.page_size + 1
        if need_total > self.lane_pages:
            # checked BEFORE any ref is committed: an over-long prompt
            # must raise cleanly, not corrupt the pool mid-claim
            raise ValueError(
                f"prompt of {len(tokens)} tokens needs {need_total} logical "
                f"pages; a lane holds {self.lane_pages}")
        chunks = self._chunks(tokens) if self.prefix_cache else []
        path = self._match_path(chunks)
        # commit the matched refs FIRST: revived pages leave _cached, so
        # the availability check below sees the true reclaimable count and
        # eviction can no longer touch the matched path (ref > 0)
        for n in path:
            if self.ref[n.page] == 0:
                del self._cached[n.page]
            self.ref[n.page] += 1
        fresh = need_total - len(path)  # incl. any host-revived pages
        if fresh > self.free_pages:
            for n in reversed(path):  # unwind: nothing committed
                self.ref[n.page] -= 1
                if self.ref[n.page] == 0:
                    self._cached[n.page] = n
            return None
        # grab matched host payloads BEFORE drawing pages: a draw can
        # trigger more spills, and the store's capacity pressure could
        # evict an entry this alloc is about to revive (the local
        # reference keeps the payload alive either way — the tier is
        # inclusive, see HostPageStore.get)
        host_keys = self._match_host(chunks, path)
        payloads = []
        for k in host_keys:
            try:
                payloads.append(self.host_store.get(k))
            except (KeyError, ValueError):
                # _match_host's membership check raced a sibling
                # replica's eviction (KeyError) or the file failed its
                # crc (ValueError — the disk store unlinks it): this key
                # and every key after it (unattendable without it) read
                # as misses and fall through to fresh prefill. The trie
                # refs committed above stay valid either way, and the
                # pool draw is unchanged (a revived page and a fresh
                # page cost the same), so nothing needs unwinding.
                break
        host_keys = host_keys[:len(payloads)]
        row = self.tables[lane]
        row[:] = 0
        for i, n in enumerate(path):
            row[i] = n.page
        parent = path[-1] if path else self._root
        revive = []
        for j, key in enumerate(host_keys):
            # revived pages re-enter the trie as regular registered pages
            # (refcount 1, shareable immediately) at fresh physical homes
            page = self._take_page()
            self.ref[page] = 1
            row[len(path) + j] = page
            node = _TrieNode(key[-1], page, parent)
            parent.children[key[-1]] = node
            self._node_of_page[page] = node
            parent = node
            revive.append((page, payloads[j]))
        for i in range(len(path) + len(host_keys), need_total):
            page = self._take_page()
            self.ref[page] = 1
            row[i] = page
        if revive:
            self._revive_fn(revive)
        self.alloc_counts[lane] = need_total
        self.shared_counts[lane] = len(path) + len(host_keys)
        self.version += 1
        return (len(path) + len(host_keys)) * self.page_size

    def register_prefix(self, lane: int, tokens) -> None:
        """Insert ``lane``'s freshly-prefilled FULL prompt pages into the
        trie so later prompts can share them. Idempotent over the already-
        matched prefix; only refcount-1 pages this lane exclusively owns
        are ever registered (the copy-on-write invariant: pages become
        shareable exactly when they will never be written again)."""
        if not self.prefix_cache:
            return
        node = self._root
        row = self.tables[lane]
        for i, c in enumerate(self._chunks(tokens)):
            nxt = node.children.get(c)
            if nxt is None:
                nxt = _TrieNode(c, int(row[i]), node)
                node.children[c] = nxt
                self._node_of_page[nxt.page] = nxt
            node = nxt

    def ensure_page(self, lane: int, pos: int) -> bool:
        """Make logical position ``pos`` writable for ``lane`` (grow-on-
        demand: the engine calls this before each decode tick's write).
        False = the pool is dry (caller retires the request), or ``pos``
        is past the lane's logical capacity."""
        li = pos // self.page_size
        if li < self.alloc_counts[lane]:
            return True
        if li >= self.lane_pages:
            return False
        page = self._take_page()
        if page is None:
            return False
        self.ref[page] = 1
        self.tables[lane, li] = page
        self.alloc_counts[lane] = li + 1
        self.version += 1
        return True

    def ensure_span(self, lane: int, pos: int, n: int) -> int:
        """Make as many of logical positions ``[pos, pos + n)`` writable
        for ``lane`` as the pool can supply, allocating pages in order
        (the speculative-decoding verify write: one slot for the pending
        token plus up to k draft tokens). Returns the count of LEADING
        covered positions — the engine clamps the lane's draft length to
        ``covered - 1`` so no accepted token's K/V can ever land on the
        trash page, while the un-covered tail's writes route there
        harmlessly (rejected-draft territory by construction)."""
        covered = 0
        for i in range(n):
            if not self.ensure_page(lane, pos + i):
                break
            covered += 1
        return covered

    def trim_lane(self, lane: int, live_tokens: int) -> int:
        """Release ``lane``'s pages beyond those covering its
        ``live_tokens`` valid positions — the speculative tick's
        post-verify cleanup, returning rejected-draft pages to the pool
        the same tick so a lane's transient draft window can never
        starve a NEIGHBOR'S next pending-token allocation (the plain
        engine would not have held those pages, and byte parity demands
        identical ``cache_full`` decisions). Only unshared
        (refcount-1, trie-unregistered) tail pages are eligible — draft
        pages always are, prompt/prefix pages always sit inside the
        live span. Returns the number of pages released."""
        need = (max(int(live_tokens), 1) - 1) // self.page_size + 1
        freed = 0
        for i in range(int(self.alloc_counts[lane]) - 1, need - 1, -1):
            page = int(self.tables[lane, i])
            if self.ref[page] != 1 or page in self._node_of_page:
                break  # shared/registered page past the live span:
            self.ref[page] = 0  # structurally impossible — stop cold
            self._free.append(page)
            self.tables[lane, i] = 0
            self.alloc_counts[lane] = i
            freed += 1
        if freed:
            self.version += 1
        return freed

    def check_invariants(self) -> None:
        """Assert the pool's conservation/refcount invariants; raises
        AssertionError with a specific message on any breach. The chaos
        suite calls this after EVERY injected failure — a rolled-back or
        recovered tick must leave the allocator exactly as consistent as a
        clean one (docs/RESILIENCE.md)."""
        # conservation: every usable page is free, cached, or lane-held
        held = set()
        for lane in range(self.lanes):
            n = int(self.alloc_counts[lane])
            for i in range(n):
                p = int(self.tables[lane, i])
                assert p != 0, f"lane {lane} logical page {i} maps to trash"
                held.add(p)
            for i in range(n, self.lane_pages):
                assert self.tables[lane, i] == 0, (
                    f"lane {lane} logical page {i} beyond alloc_count {n} "
                    f"is {self.tables[lane, i]}, not trash")
        free = set(self._free)
        cached = set(self._cached)
        assert not (free & cached), f"pages both free and cached: {free & cached}"
        assert not (free & held), f"pages both free and lane-held: {free & held}"
        assert not (cached & held), (
            f"pages both cached and lane-held: {cached & held}")
        assert free | cached | held == set(range(1, self.num_pages)), (
            "page conservation broken: "
            f"{len(free)} free + {len(cached)} cached + {len(held)} held "
            f"!= {self.num_pages - 1} usable")
        # refcounts: trash pinned, cached zero-ref, held = #lanes holding
        assert self.ref[0] >= 1, "trash page unpinned"
        counts = {p: 0 for p in range(1, self.num_pages)}
        for lane in range(self.lanes):
            for i in range(int(self.alloc_counts[lane])):
                counts[int(self.tables[lane, i])] += 1
        for p in range(1, self.num_pages):
            want = counts[p]
            assert self.ref[p] == want, (
                f"page {p} refcount {self.ref[p]} != {want} lane holders")
            if p in cached or p in free:
                assert want == 0
        # trie: every cached page has a live node; parent >= child refs
        for p, node in self._cached.items():
            assert self._node_of_page.get(p) is node, (
                f"cached page {p} lost its trie node")
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            for c in n.children.values():
                assert self.ref[c.page] <= self.ref[n.page], (
                    f"trie child page {c.page} (ref {self.ref[c.page]}) "
                    f"outlives parent {n.page} (ref {self.ref[n.page]})")
                stack.append(c)
        # host tier: byte accounting exact, and no key shadows a LIVE trie
        # path (a spilled entry for a path that is back in the trie is
        # merely stale-but-valid — content-addressed keys cannot be wrong
        # — but the trie must win the match, so it never revives)
        if self.host_store is not None:
            self.host_store.check_invariants()

    def free(self, lane: int) -> None:
        """Release every page of ``lane``'s chain (refcount--). Zero-ref
        pages return to the free stack — unless they are trie-registered,
        in which case they park in the LRU cache with content intact so
        the next matching prompt revives them for free."""
        if not self.alloc_counts[lane]:
            raise ValueError(f"lane {lane} holds no pages (double-freed?)")
        row = self.tables[lane]
        for i in range(int(self.alloc_counts[lane])):
            page = int(row[i])
            if self.ref[page] <= 0:
                raise ValueError(
                    f"page {page} of lane {lane} double-freed")
            self.ref[page] -= 1
            if self.ref[page] == 0:
                node = self._node_of_page.get(page)
                if node is not None:
                    self._cached[page] = node
                else:
                    self._free.append(page)
        row[:] = 0
        self.alloc_counts[lane] = 0
        self.shared_counts[lane] = 0
        self.version += 1


class PagedKVCacheManager(_LaneBook):
    """Page-granular decode cache + lane bookkeeping (the paged sibling of
    :class:`SlotKVCacheManager`; module docstring has the design).

    Decode *lanes* (batch rows of the jitted step) are still allocated
    lowest-free-first like slots — ``free_count``/``active_count`` keep
    their slot-era meaning — but storage admission is by PAGES: a lane is
    only claimable when :class:`PagePool` can cover the prompt, and the
    chain grows page-by-page as the request decodes. ``cache`` is the live
    device tree of ``[num_pages, page_size, heads, head_dim]`` leaves;
    ``tables`` the host block tables the engine uploads when ``version``
    moves."""

    def __init__(self, model, slots: int, cache_len: int, num_pages: int,
                 page_size: int, prefix_cache: bool = True,
                 host_store: Optional[HostPageStore] = None):
        from fleetx_tpu.models.gpt.generation import init_decode_cache

        if page_size % 8:
            raise ValueError(
                f"page_size must be a multiple of 8 (flash-decode tiling "
                f"contract), got {page_size}")
        if cache_len % page_size:
            raise ValueError(
                f"cache_len {cache_len} must be a multiple of page_size "
                f"{page_size}")
        cfg = model.cfg
        if (cfg.decode_cache_len, cfg.decode_num_pages,
                cfg.decode_page_size) != (cache_len, num_pages, page_size):
            raise ValueError(
                "model cfg (decode_cache_len, decode_num_pages, "
                f"decode_page_size) = ({cfg.decode_cache_len}, "
                f"{cfg.decode_num_pages}, {cfg.decode_page_size}) must "
                f"match the manager's ({cache_len}, {num_pages}, "
                f"{page_size})")
        self._init_lanes(slots)
        self.cache_len = cache_len
        self.page_size = page_size
        self.num_pages = num_pages
        self.host_store = host_store
        self._revive_jit = self._make_revive_jit()
        self.pool = PagePool(num_pages, page_size, slots,
                             cache_len // page_size, prefix_cache,
                             host_store=host_store,
                             spill_fn=self._spill_pages,
                             revive_fn=self._revive_pages)
        self.cache = init_decode_cache(model, slots)

    # ------------------------------------------------------ host spill tier

    def _spill_pages(self, pages: List[int]) -> List[Tuple[list, int]]:
        """Read ``pages`` out of the device pool as host payloads — one
        batched gather + transfer per cache leaf for the whole list (the
        subtree being evicted), not one per page. A payload is the
        per-leaf list of that page's slices (K, V, and the int8 scale
        pages when quantized); rank-<4 leaves (``cache_index`` scalars)
        ride as None."""
        import jax.numpy as jnp

        from fleetx_tpu.obs.events import emit as obs_emit

        idx = jnp.asarray(pages, jnp.int32)
        per_leaf = []
        for leaf in jax.tree.leaves(self.cache):
            if leaf.ndim < 4:
                per_leaf.append(None)
                continue
            ax = leaf.ndim - 4  # the page axis (scan-stacked or unrolled)
            taken = jnp.moveaxis(jnp.take(leaf, idx, axis=ax), ax, 0)
            per_leaf.append(np.asarray(jax.device_get(taken)))
        out = []
        for j in range(len(pages)):
            payload = [None if a is None else a[j] for a in per_leaf]
            nbytes = sum(a.nbytes for a in payload if a is not None)
            out.append((payload, nbytes))
        obs_emit("page_spill", pages=len(pages))
        return out

    def _make_revive_jit(self):
        """Jitted batched revival: one scatter per cache leaf, with the
        old pool buffers DONATED on TPU so XLA updates the pages in
        place — an eager ``.at[].set`` would copy every full-size pool
        leaf per revival, transiently doubling the cache's HBM footprint
        the engine's donation discipline exists to avoid. jax.jit's own
        shape-keyed cache gives one compile per distinct batch size
        (bounded by lane_pages, like the engine's prefill buckets)."""

        def revive(leaves, pages, updates):
            out = []
            for leaf, upd in zip(leaves, updates):
                ax = leaf.ndim - 4
                index = (slice(None),) * ax + (pages,)
                out.append(leaf.at[index].set(upd))
            return out

        donate = jax.default_backend() in ("tpu", "axon")
        return jax.jit(revive, donate_argnums=(0,) if donate else ())

    def _revive_pages(self, entries: List[Tuple[int, list]]) -> None:
        """Write spilled payloads back into fresh physical ``pages`` —
        one batched host→device transfer + in-place scatter per cache
        leaf for every page an alloc revives (the "batched device_put"
        the revive path promises)."""
        import jax.numpy as jnp

        from fleetx_tpu.obs.events import emit as obs_emit

        pages = jnp.asarray([p for p, _ in entries], jnp.int32)
        leaves, treedef = jax.tree.flatten(self.cache)
        big = [i for i, leaf in enumerate(leaves) if leaf.ndim >= 4]
        updates = [
            np.moveaxis(np.stack([payload[i] for _, payload in entries]),
                        0, leaves[i].ndim - 4)
            for i in big
        ]
        new = self._revive_jit([leaves[i] for i in big], pages, updates)
        for i, leaf in zip(big, new):
            leaves[i] = leaf
        self.cache = jax.tree.unflatten(treedef, leaves)
        obs_emit("page_revive", pages=len(entries))

    # --------------------------------------------- cross-replica page ship
    # (docs/SERVING.md "Disaggregated prefill/decode"): a prefill-role
    # replica reads a finished prompt's pages out through the SAME
    # batched per-leaf device reads the spill tier uses, and a decode-
    # role replica writes shipped payloads into its own fresh pages
    # through the SAME batched revive scatter — the ship path adds no new
    # device code, only the public names.

    def read_pages(self, pages: List[int]) -> List[list]:
        """Read physical ``pages`` out of the device pool as host
        payloads (one per page, each a per-cache-leaf list with None for
        rank-<4 leaves — exactly what :meth:`HostPageStore
        .payload_to_bytes` serializes). One batched gather + transfer
        per cache leaf for the whole list, int8 scale pages included."""
        return [payload for payload, _ in self._spill_pages(pages)]

    def revive_pages(self, entries: List[Tuple[int, list]]) -> None:
        """Write ``(physical_page, payload)`` entries into the device
        pool — the decode-role half of a KV handoff, one batched
        host→device transfer + in-place scatter per cache leaf. The
        caller owns the bookkeeping: the pages must already be allocated
        to the receiving lane (``alloc``) and their payloads decoded and
        validated (``payload_from_bytes`` raises on corruption)."""
        self._revive_pages(entries)

    # ------------------------------------------------------- page surface

    @property
    def tables(self) -> np.ndarray:
        """Host block tables [slots, cache_len // page_size] int32."""
        return self.pool.tables

    @property
    def tables_version(self) -> int:
        """Monotone counter: re-upload the device tables when it moves."""
        return self.pool.version

    @property
    def pages_in_use(self) -> int:
        """Pages pinned by live requests (trash page excluded)."""
        return self.pool.pages_in_use

    @property
    def usable_pages(self) -> int:
        """Pages the pool can hand to requests."""
        return self.pool.usable_pages

    def page_occupancy(self) -> float:
        """Fraction of usable pages pinned by live requests."""
        return self.pool.occupancy()

    # ---------------------------------------------------------- lifecycle

    def can_admit(self, tokens) -> bool:
        """A free lane AND enough free pages for this prompt right now."""
        return bool(self._free) and self.pool.can_admit(tokens)

    def alloc(self, request_id: int, tokens) -> Optional[Tuple[int, int]]:
        """Claim the lowest free lane + a page chain for prompt ``tokens``.
        Returns ``(lane, shared_len)`` — ``shared_len`` tokens of trie-
        shared prefix whose prefill is skipped — or None (nothing claimed)
        when lanes or pages are short."""
        if not self._free:
            return None
        if len(tokens) >= self.cache_len:
            # >= not >: a full-capacity prompt would need lane_pages + 1
            # logical pages (the first sampled token's slot) — and has no
            # decode room anyway, mirroring the engine's submit() guard
            raise ValueError(
                f"prompt_len {len(tokens)} leaves no decode room "
                f"(cache_len {self.cache_len})")
        lane = self._free[0]  # peek: only claim once pages are certain
        shared = self.pool.alloc(lane, tokens)
        if shared is None:
            return None
        claimed = self._claim_lane(request_id, len(tokens))
        assert claimed == lane  # heap head == the lane the pool filled
        return lane, shared

    def register_prefix(self, slot: int, tokens) -> None:
        """Publish ``slot``'s freshly-prefilled full prompt pages for
        sharing (see :meth:`PagePool.register_prefix`)."""
        self.pool.register_prefix(slot, tokens)

    def ensure_page(self, slot: int) -> bool:
        """Grow ``slot``'s chain to cover its next write position
        (``lengths[slot]``); False = pool dry, caller retires the
        request."""
        return self.pool.ensure_page(slot, int(self.lengths[slot]))

    def ensure_span(self, slot: int, n: int) -> int:
        """Grow ``slot``'s chain toward covering its next ``n`` write
        positions (the speculative verify window: pending token + k
        drafts); returns how many leading positions are covered — see
        :meth:`PagePool.ensure_span` for the draft-clamp contract."""
        return self.pool.ensure_span(slot, int(self.lengths[slot]), n)

    def trim_span(self, slot: int) -> int:
        """Release ``slot``'s pages past its live prefix (rejected-draft
        territory) back to the pool — see :meth:`PagePool.trim_lane`."""
        return self.pool.trim_lane(slot, int(self.lengths[slot]))

    def free(self, slot: int) -> None:
        """Release the lane and its page chain. No buffer zeroing — the
        live-window contract (module docstring) plus zeroed table entries
        (all writes re-route to the trash page) keep stale K/V dark."""
        if self.request_ids[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        self.pool.free(slot)
        self._release_lane(slot)
