"""KV-free dynamic-batching serving engine base (the simplest engine).

``ServingEngine`` earns its complexity from the KV cache: slots, pages,
spill tiers, replay recovery all exist because autoregressive decode
carries device state between ticks. Encoder-style models carry NONE —
an ERNIE fill-in-blank scoring call or a ViT embedding is one batched
forward — so their engine is pure request coalescing: admit up to
``slots`` queued requests per tick, bucket them into padded batches,
run one jitted forward per bucket, emit every output, retire. No cache
pool, no slot lifecycle beyond the duration of a single ``step()``.

What it KEEPS from the big engine is the operational contract
(serving/model_protocol.py ``ENGINE_SURFACE``), so routers, the API
layer, and the chaos tooling apply unmodified:

- **Admission**: bounded queue (``FLEETX_SERVING_MAX_QUEUE`` →
  :class:`QueueFull`), drain rejects (:class:`ShuttingDown`),
  queue-TTL and total-deadline shedding to ``finish_reason="timeout"``.
- **Exactly one terminal result** per submit: ``complete`` on success
  (the encoder analogue of ``eos`` — there is nothing to decode
  further), ``timeout`` / ``cancelled`` / ``error`` / ``shutdown``
  exactly as the big engine defines them.
- **Fault discipline**: the forward runs under the same
  ``faults.on_serving_tick`` seam; a raising call requeues the batch at
  the head (arrival order preserved — outputs were never emitted, so
  the retry is trivially byte-identical), strikes the requests, and
  after ``max_recoveries`` consecutive strikes retires them as
  ``error`` instead of spinning (``tick_fault`` / ``engine_recovery``
  events banked, same names the chaos assertions grep for).
- **Observability**: the standard ``fleetx_serving_*`` families via
  ``ServingMetrics``, plus the dynamic-batching pair
  (``fleetx_serving_batched_forwards_total``,
  ``fleetx_serving_batch_occupancy``) — docs/OBSERVABILITY.md.
- **Migration**: deterministic forwards make failover trivial — a
  request re-submitted with ``history=`` (the router's durable copy)
  re-runs and emits only the tokens past the history, byte-identical.

Output tokens are the WIRE ENCODING of the model's answer: token ids
for fill-in-blank, a class id for classification, or a float32 vector
bit-cast to int32 for embeddings (lossless; ``decode_floats`` in
serving/embedding_engine.py inverts it). Riding the int32 token channel
end to end is what lets every router/recovery/chaos invariant — built
for token streams — hold for non-token models without modification.

Concrete engines: ``ErnieScoringEngine`` (serving/ernie_engine.py) and
``EmbeddingEngine`` (serving/embedding_engine.py). docs/SERVING.md
"Heterogeneous fleet" has the architecture.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import numpy as np

from fleetx_tpu.obs.events import emit as obs_emit
from fleetx_tpu.obs.tracing import span
from fleetx_tpu.resilience.faults import faults
from fleetx_tpu.serving.engine import (
    QueueFull,
    ServingResult,
    ShuttingDown,
    _env_float,
    _env_int,
)
from fleetx_tpu.serving.metrics import ServingMetrics
from fleetx_tpu.serving.model_protocol import ModelCapabilities
from fleetx_tpu.serving.scheduler import FIFOScheduler, Request
from fleetx_tpu.utils.log import logger

__all__ = ["BatchingEngine"]


def _bucket(n: int, cap: int) -> int:
    """Next power of two >= n, capped — bounds distinct jit shapes."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class BatchingEngine:
    """Dynamic-batching engine over one encoder-style model (module
    docstring). Subclasses set ``capabilities`` / ``cache_len`` and
    implement ``_validate(prompt)`` + ``_run_batch(requests)``."""

    #: subclasses override (ModelCapabilities of the served family)
    capabilities: ModelCapabilities

    def __init__(self, model, variables, *, slots: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 queue_ttl_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 max_recoveries: Optional[int] = None,
                 base_seed: int = 0,
                 metrics: Optional[ServingMetrics] = None):
        self.model = model
        self.params = (variables["params"] if isinstance(variables, dict)
                       and "params" in variables else variables)
        self.slots = slots or _env_int("FLEETX_SERVING_SLOTS", 8)
        self.max_queue = (max_queue if max_queue is not None
                          else _env_int("FLEETX_SERVING_MAX_QUEUE", 0))
        self.queue_ttl_s = (queue_ttl_s if queue_ttl_s is not None
                            else _env_float("FLEETX_SERVING_QUEUE_TTL_S",
                                            0.0))
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("FLEETX_SERVING_DEADLINE_S", 0.0))
        self.grace_s = (grace_s if grace_s is not None
                        else _env_float("FLEETX_SERVING_GRACE_S", 30.0))
        self.max_recoveries = max(1, max_recoveries if max_recoveries
                                  is not None
                                  else _env_int(
                                      "FLEETX_SERVING_MAX_RECOVERIES", 8))
        # router-facing shape attrs (ENGINE_SURFACE): a KV-free engine is
        # never paged, never phase-split, and its "cache length" is just
        # its per-request input bound
        self.role = "both"
        self.paged = False
        self.page_size = 0
        self.model_family = self.capabilities.family
        self.cache_len = self.capabilities.max_input
        self.scheduler = FIFOScheduler()
        self.metrics = metrics or ServingMetrics(self.slots)
        self._base_key = jax.random.PRNGKey(base_seed)
        self._results: Dict[int, ServingResult] = {}
        self._strikes: Dict[int, int] = {}
        self._next_id = 0
        self._ticks = 0
        self._fault_ticks = 0
        self._recovery_streak = 0
        self._shutting_down = False
        self._shutdown_deadline: Optional[float] = None
        self._dead = False
        self._now = time.perf_counter  # swappable clock (chaos tests)

    # ---------------------------------------------------- subclass hooks

    def _validate(self, prompt: np.ndarray) -> None:
        """Raise ValueError when ``prompt`` is not servable here — the
        heterogeneous-rejection seam the router turns into try-the-
        others / clean error."""
        raise NotImplementedError

    def _run_batch(self, requests: List[Request]) -> List[List[int]]:
        """One coalesced device call: the wire-encoded output token list
        for each request, in order. Runs under the fault seam — raise
        freely; the base requeues and retries."""
        raise NotImplementedError

    # ------------------------------------------------------------ submit

    def submit(self, prompt, *, on_token=None, seed: Optional[int] = None,
               rng_key: Optional[jax.Array] = None,
               queue_ttl_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               history=None, kv_payloads=None,
               max_length: Optional[int] = None,
               min_length: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               decode_strategy: Optional[str] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None) -> int:
        """Queue one request; returns its id. The signature is the
        ENGINE_SURFACE submit: sampling knobs are accepted (a router
        forwards whatever the caller set) and IGNORED — every forward
        here is deterministic, so there is no stream to steer. A
        non-None ``kv_payloads`` is a placement bug and rejects with
        ValueError (no KV cache to revive into); ``history`` replays a
        migrated request (the deterministic forward re-derives the same
        outputs and ``on_token`` fires only past the history)."""
        del max_length, min_length, eos_token_id, decode_strategy
        del temperature, top_k, top_p  # deterministic encoder: no knobs
        if self._shutting_down:
            self.metrics.record_drain_reject()
            obs_emit("drain_reject", engine=self.metrics.engine_label)
            raise ShuttingDown(
                "engine is draining toward shutdown; submit to another "
                "replica")
        if self.max_queue and self.scheduler.queue_depth >= self.max_queue:
            self._expire_queued(self._now())
        if self.max_queue and self.scheduler.queue_depth >= self.max_queue:
            self.metrics.record_reject()
            obs_emit("queue_reject", engine=self.metrics.engine_label,
                     queue_depth=self.scheduler.queue_depth)
            raise QueueFull(
                f"admission queue is full ({self.scheduler.queue_depth}/"
                f"{self.max_queue} waiting); retry later or raise "
                "FLEETX_SERVING_MAX_QUEUE")
        if kv_payloads is not None:
            raise ValueError(
                f"model family {self.model_family!r} has no KV cache to "
                "revive shipped pages into (capabilities.has_kv_cache="
                "False) — this engine cannot take a disaggregated handoff")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        self._validate(prompt)
        rid = self._next_id
        self._next_id += 1
        if rng_key is None:
            rng_key = (jax.random.PRNGKey(int(seed)) if seed is not None
                       else jax.random.fold_in(self._base_key, rid))
        req = Request(
            id=rid, prompt=prompt, max_new_tokens=1, min_new_tokens=0,
            eos_token_id=-1, greedy=True, temperature=1.0, top_k=0,
            top_p=1.0, rng_key=rng_key, on_token=on_token,
            submit_time=self._now(),
            queue_ttl_s=float(queue_ttl_s if queue_ttl_s is not None
                              else self.queue_ttl_s),
            deadline_s=float(deadline_s if deadline_s is not None
                             else self.deadline_s),
        )
        if history is not None:
            # migrated replay: the router's durable copy of what the
            # caller already saw; the deterministic forward re-derives
            # the full output and emission skips this prefix
            req.tokens.extend(int(t) for t in
                              np.asarray(history, np.int64).reshape(-1))
        self.scheduler.submit(req)
        self.metrics.record_submit()
        return rid

    # -------------------------------------------------------------- step

    def step(self) -> Dict:
        """One tick: shed expired queued work, coalesce up to ``slots``
        requests into one batched forward (the fault seam wraps it),
        emit outputs, retire. Returns a summary dict shaped like the big
        engine's (``retired``/``timed_out``/``queue_depth``/...)."""
        t0 = self._now()
        self._ticks += 1
        timed_out = self._expire_queued(t0)
        retired: List[int] = []
        recovered = False
        if (self._shutting_down and self._shutdown_deadline is not None
                and t0 > self._shutdown_deadline):
            retired.extend(self._retire_all("shutdown"))
        batch: List[Request] = []
        while len(batch) < self.slots:
            req = self.scheduler.pop_next()
            if req is None:
                break
            batch.append(req)
        forwards = 0
        if batch:
            attempt = self._fault_ticks
            self._fault_ticks += 1
            try:
                with span("serving.batch_forward", engine_tick=self._ticks,
                          batch=len(batch)):
                    faults.on_serving_tick(attempt)
                    outputs = self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — requeue-and-retry seam
                recovered = True
                retired.extend(self._on_batch_fault(batch, e))
            else:
                forwards = 1
                self._recovery_streak = 0
                self.metrics.record_batched_forward(len(batch), self.slots)
                now = self._now()
                for req, out in zip(batch, outputs):
                    self._strikes.pop(req.id, None)
                    self._emit_and_finalize(req, out, now)
                    retired.append(req.id)
        self.metrics.observe_tick(self.scheduler.queue_depth, 0,
                                  self._now() - t0)
        return {"admitted": len(batch), "retired": retired,
                "timed_out": timed_out, "forwards": forwards,
                "recovered": recovered,
                "queue_depth": self.scheduler.queue_depth}

    def _emit_and_finalize(self, req: Request, out: List[int],
                           now: float) -> None:
        """Deliver one request's outputs and record its terminal
        result. History tokens (migrated replay) are skipped on the
        callback — the caller already has them — but ride the result."""
        already = len(req.tokens)
        out = [int(t) for t in out]
        req.tokens = out
        req.admit_time = now
        self.metrics.record_admit(now - req.submit_time)
        cb_error = False
        for i, tok in enumerate(out[already:]):
            if req.first_token_time is None:
                req.first_token_time = self._now()
                self.metrics.record_first_token(
                    req.first_token_time - req.submit_time)
            if req.on_token is not None and not cb_error:
                try:
                    req.on_token(req.id, tok,
                                 already + i + 1 == len(out))
                except Exception:  # noqa: BLE001 — caller bug, not ours
                    cb_error = True
                    logger.exception(
                        "serving: on_token callback raised for request "
                        "%d; delivery stops, result still records", req.id)
        self.metrics.record_tokens(len(out) - already)
        self._finalize(req, "error" if cb_error else "complete", self._now())

    def _on_batch_fault(self, batch: List[Request], err: Exception
                        ) -> List[int]:
        """The KV-free recovery path: nothing was emitted, so retry is
        requeue-at-head in arrival order; requests that keep striking
        retire as ``error`` (the poison analogue), and the engine
        declares itself dead past ``max_recoveries`` consecutive
        faulted ticks."""
        obs_emit("tick_fault", engine=self.metrics.engine_label,
                 error=f"{type(err).__name__}: {err}", batch=len(batch))
        logger.warning(
            "serving: batched forward over %d request(s) raised (%s); "
            "requeueing at head", len(batch), err)
        now = self._now()
        dead = []
        for req in reversed(batch):
            self._strikes[req.id] = self._strikes.get(req.id, 0) + 1
            if self._strikes[req.id] > self.max_recoveries:
                self._strikes.pop(req.id, None)
                self._finalize(req, "error", now)
                dead.append(req.id)
            else:
                self.scheduler.requeue(req)
        self.metrics.record_recovery()
        self._recovery_streak += 1
        obs_emit("engine_recovery", engine=self.metrics.engine_label,
                 streak=self._recovery_streak)
        if self._recovery_streak > self.max_recoveries:
            self._dead = True
        return dead

    def _expire_queued(self, now: float) -> List[int]:
        expired = self.scheduler.pop_expired(now)
        out = []
        for req in expired:
            self._finalize(req, "timeout", now)
            obs_emit("request_timeout", request=req.id, where="queue")
            out.append(req.id)
        return out

    def _finalize(self, req: Request, reason: str, now: float) -> None:
        if req.id in self._results:
            return  # exactly-one-result: first terminal reason wins
        req.phase = "finished"
        self._results[req.id] = ServingResult(
            id=req.id, prompt=req.prompt,
            tokens=np.asarray(req.tokens, np.int32),
            finish_reason=reason,
            ttft_s=(req.first_token_time or now) - req.submit_time,
            latency_s=now - req.submit_time,
        )
        self.metrics.record_retire(now - req.submit_time, reason)

    # ------------------------------------------------- results/lifecycle

    def result(self, request_id: int) -> Optional[ServingResult]:
        """Finished result for ``request_id`` (None while in flight)."""
        return self._results.get(request_id)

    def take_result(self, request_id: int) -> Optional[ServingResult]:
        """Remove and return one finished result (None while queued)."""
        return self._results.pop(request_id, None)

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued request: exactly one terminal result with
        ``finish_reason="cancelled"``. False when unknown/finished
        (requests are only ever in-flight INSIDE one step() call, so
        between ticks everything unfinished is queued)."""
        req = self.scheduler.remove(request_id)
        if req is None:
            return False
        self._finalize(req, "cancelled", self._now())
        obs_emit("request_cancelled", request=request_id,
                 engine=self.metrics.engine_label)
        return True

    def emitted_tokens(self, request_id: int) -> Optional[list]:
        """Host-truth tokens of a live request (its migrated-history
        prefix; a KV-free engine emits everything else atomically at
        retirement). None for unknown/finished ids."""
        for r in self.scheduler.snapshot():
            if r.id == request_id:
                return list(r.tokens)
        return None

    def request_shutdown(self, grace_s: Optional[float] = None) -> None:
        """Flip into draining mode: submits reject, queued work finishes
        until the grace deadline, leftovers retire as ``shutdown``."""
        if self._shutting_down:
            return
        self._shutting_down = True
        grace = self.grace_s if grace_s is None else float(grace_s)
        self._shutdown_deadline = self._now() + max(grace, 0.0)
        obs_emit("shutdown", engine=self.metrics.engine_label,
                 active=0, queued=self.scheduler.queue_depth)

    def shutdown(self, grace_s: Optional[float] = None
                 ) -> Dict[int, ServingResult]:
        """Graceful drain to completion; every submitted request gets a
        terminal result."""
        self.request_shutdown(grace_s)
        while len(self.scheduler):
            self.step()
        out, self._results = self._results, {}
        return out

    def drain(self, max_ticks: Optional[int] = None
              ) -> Dict[int, ServingResult]:
        """Tick until the queue is empty (or ``max_ticks``), then
        return-and-clear every finished result."""
        n = 0
        while len(self.scheduler):
            self.step()
            n += 1
            if max_ticks is not None and n >= max_ticks:
                break
        out, self._results = self._results, {}
        return out

    def _retire_all(self, reason: str) -> List[int]:
        now = self._now()
        out = []
        for req in self.scheduler.drain_all():
            self._finalize(req, reason, now)
            out.append(req.id)
        return out

    def declare_dead(self) -> None:
        """Mark the engine dead without shutdown machinery (the
        supervisor/router seam — see ServingEngine.declare_dead)."""
        self._dead = True

    # ------------------------------------------------------ health/shape

    def health(self) -> Dict:
        """The ``/healthz`` JSON body (ENGINE_SURFACE): drain-aware
        state plus the model family + capability flags the model-aware
        router groups replicas by."""
        state = ("dead" if self._dead
                 else "draining" if self._shutting_down else "ok")
        return {"state": state,
                "role": self.role,
                "model": self.model_family,
                "capabilities": self.capabilities.as_dict(),
                "queue_depth": self.scheduler.queue_depth,
                "queue_tokens": self.scheduler.queued_tokens(),
                "active": 0,
                "slots": self.slots}

    @property
    def submit_limit(self) -> int:
        """Smallest rejected per-request input size (router admission
        bound): a KV-free request needs no decode room, so the bound is
        one past the model's input capacity."""
        return self.cache_len + 1
