"""ERNIE fill-in-blank / scoring engine (encoder-style serving).

``ErnieForPretraining`` is a bidirectional encoder: one forward over
the whole sequence yields MLM logits at chosen positions plus a
sentence-order (SOP) head — there is no autoregressive loop, so the
engine is a :class:`~fleetx_tpu.serving.batch_engine.BatchingEngine`
over dynamic padded batches. Two request shapes ride the same submit:

- **Fill-in-blank**: a prompt containing mask tokens
  (``FLEETX_ERNIE_MASK_ID``, default 3 — ERNIE-1.0's ``[MASK]``). The
  engine finds the mask positions, runs the batched forward with a
  fixed-size ``masked_positions`` gather (padded to
  ``FLEETX_ERNIE_MAX_MASKS`` so every batch traces the same program),
  and emits the argmax token id per blank, in prompt order.
- **Scoring**: a prompt with NO masks. The output is one token — the
  SOP head's argmax (0 = coherent ordering, 1 = swapped) — the
  cheapest useful whole-sequence judgment the pretraining heads give.

Batches are bucketed on (batch→pow2, padded-length→pow2) like the GPT
prefill path, so distinct jit traces stay logarithmic in both axes.
Padding rows use ``pad_token_id`` with an explicit attention mask, so
padded and unpadded runs agree. docs/SERVING.md "Heterogeneous fleet".
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np

from fleetx_tpu.serving.batch_engine import BatchingEngine, _bucket
from fleetx_tpu.serving.engine import _env_int
from fleetx_tpu.serving.model_protocol import ModelCapabilities

__all__ = ["ErnieScoringEngine"]


class ErnieScoringEngine(BatchingEngine):
    """Dynamic-batching fill-in-blank / SOP-scoring over one ERNIE
    model (module docstring)."""

    def __init__(self, model, variables, *,
                 mask_token_id: Optional[int] = None,
                 max_masks: Optional[int] = None, **kw):
        self.capabilities = ModelCapabilities(
            family="ernie",
            has_kv_cache=False,
            supports_spec=False,
            cache_layout="none",
            max_input=int(model.cfg.max_position_embeddings),
        )
        super().__init__(model, variables, **kw)
        self.mask_token_id = (mask_token_id if mask_token_id is not None
                              else _env_int("FLEETX_ERNIE_MASK_ID", 3))
        self.max_masks = max(1, max_masks if max_masks is not None
                             else _env_int("FLEETX_ERNIE_MAX_MASKS", 8))

        def fwd(params, ids, mask, positions):
            mlm, sop = model.apply({"params": params}, ids,
                                   attention_mask=mask,
                                   masked_positions=positions,
                                   deterministic=True)
            return (jax.numpy.argmax(mlm, axis=-1),
                    jax.numpy.argmax(sop, axis=-1))

        self._fwd = jax.jit(fwd)

    def _validate(self, prompt: np.ndarray) -> None:
        if prompt.size > self.cache_len:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds the ERNIE input "
                f"capacity ({self.cache_len})")
        n_masks = int((prompt == self.mask_token_id).sum())
        if n_masks > self.max_masks:
            raise ValueError(
                f"prompt holds {n_masks} mask tokens but the engine "
                f"gathers at most {self.max_masks} "
                "(FLEETX_ERNIE_MAX_MASKS)")

    def _run_batch(self, requests) -> List[List[int]]:
        pad_id = int(self.model.cfg.pad_token_id)
        b = _bucket(len(requests), self.slots)
        length = _bucket(max(r.prompt.size for r in requests),
                         self.cache_len)
        ids = np.full((b, length), pad_id, np.int32)
        mask = np.zeros((b, length), np.int32)
        # fixed-size mask gather: pad with position 0 (rows with fewer
        # masks read garbage there; emission slices to the true count)
        positions = np.zeros((b, self.max_masks), np.int32)
        counts = []
        for i, r in enumerate(requests):
            ids[i, :r.prompt.size] = r.prompt
            mask[i, :r.prompt.size] = 1
            where = np.flatnonzero(r.prompt == self.mask_token_id)
            positions[i, :where.size] = where
            counts.append(int(where.size))
        mlm_ids, sop_ids = self._fwd(self.params, ids, mask, positions)
        mlm_ids = np.asarray(mlm_ids)
        sop_ids = np.asarray(sop_ids)
        out = []
        for i, n in enumerate(counts):
            if n:
                out.append([int(t) for t in mlm_ids[i, :n]])
            else:
                out.append([int(sop_ids[i])])  # scoring mode: SOP verdict
        return out
