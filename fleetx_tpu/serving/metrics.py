"""Serving observability: queue/slot/latency/throughput counters.

One :class:`ServingMetrics` instance rides a :class:`ServingEngine`; the
engine feeds it lifecycle events (submit/admit/first-token/retire) and a
per-tick gauge sample (queue depth, active slots). ``snapshot()`` returns
the aggregate dict the benches and tests consume; ``log_snapshot()``
surfaces the same line through ``utils/log.py`` (gate the cadence with
``FLEETX_SERVING_LOG_EVERY``).

TTFT here is end-to-end: submit → the request's first token is on the
host (queue wait + prefill + the device sync), which is what a caller
actually observes — first requests include compile time, so warm up
before reading latencies as steady-state.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ServingMetrics"]


def _pct(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values), q))


class ServingMetrics:
    """Counters + gauges for one serving engine (see module docstring)."""

    def __init__(self, slots: int = 0):
        self.slots = slots
        self.submitted = 0
        self.admitted = 0
        self.retired = 0
        self.rejected = 0
        self.tokens_generated = 0
        self.ticks = 0
        self.finish_reasons: Dict[str, int] = {}
        self.ttft_s: List[float] = []
        self.queue_wait_s: List[float] = []
        self.latency_s: List[float] = []
        self.queue_depth = 0
        self.active_slots = 0
        self._queue_depth_sum = 0
        self._queue_depth_peak = 0
        self._occupancy_sum = 0
        self._first_token_t: Optional[float] = None
        self._last_token_t: Optional[float] = None
        # paged-cache counters (zero/empty on the slot path so the
        # snapshot schema is stable across modes)
        self.prefix_queries = 0
        self.prefix_hits = 0
        self.prefill_tokens_saved = 0
        self.prompt_tokens = 0
        self.pages_per_request: List[int] = []
        self.pages_in_use = 0
        self.pages_total = 0
        self._page_occupancy_sum = 0.0
        self._page_occupancy_peak = 0.0
        self._page_ticks = 0
        # crash-safety counters (docs/RESILIENCE.md serving-recovery):
        # recoveries = replay-recovery passes the engine ran, poison =
        # requests quarantined by bisection/replay, drain_rejects = submits
        # refused because the engine was shutting down. Tick wall-clock
        # samples make the recovery cost observable (a recovery tick re-
        # prefills every active request, so its duration spikes).
        self.engine_recoveries = 0
        self.poison_retired = 0
        self.drain_rejects = 0
        # bounded window: one sample per tick forever would grow without
        # limit on a continuously-ticking replica (and np.percentile over
        # it would too); 4096 ticks ≈ the recent-behavior window the
        # percentiles are meant to describe
        self.tick_s = collections.deque(maxlen=4096)

    def record_submit(self) -> None:
        """A request entered the admission queue."""
        self.submitted += 1

    def record_admit(self, queue_wait_s: float) -> None:
        """A request won a slot after waiting ``queue_wait_s``."""
        self.admitted += 1
        self.queue_wait_s.append(float(queue_wait_s))

    def record_first_token(self, ttft_s: float) -> None:
        """First token of a request reached the host (end-to-end TTFT)."""
        self.ttft_s.append(float(ttft_s))

    def record_tokens(self, n: int) -> None:
        """``n`` decode tokens reached the host this tick."""
        now = time.perf_counter()
        if self._first_token_t is None:
            self._first_token_t = now
        self._last_token_t = now
        self.tokens_generated += n

    def record_reject(self) -> None:
        """A submit was refused by admission control (queue full)."""
        self.rejected += 1

    def record_recovery(self) -> None:
        """The engine ran one replay-recovery pass (device state rebuilt
        and every active request re-prefilled from its host history)."""
        self.engine_recoveries += 1

    def record_poison(self) -> None:
        """A poison request was quarantined (bisection or replay failure)
        and retired with ``finish_reason="error"``."""
        self.poison_retired += 1

    def record_drain_reject(self) -> None:
        """A submit was refused because the engine is shutting down."""
        self.drain_rejects += 1

    def record_prefix(self, shared_tokens: int, prompt_tokens: int,
                      pages: int) -> None:
        """One paged admission: ``shared_tokens`` of the prompt came from
        the prefix cache (their prefill was skipped), ``pages`` is the
        FRESH pages the request claimed (trie-shared pages excluded —
        they cost nothing, which is the point)."""
        self.prefix_queries += 1
        if shared_tokens > 0:
            self.prefix_hits += 1
        self.prefill_tokens_saved += int(shared_tokens)
        self.prompt_tokens += int(prompt_tokens)
        self.pages_per_request.append(int(pages))

    def observe_pages(self, pages_in_use: int, pages_total: int) -> None:
        """Per-tick page-pool gauge sample (paged mode only)."""
        self.pages_in_use = pages_in_use
        self.pages_total = pages_total
        occ = pages_in_use / pages_total if pages_total else 0.0
        self._page_occupancy_sum += occ
        self._page_occupancy_peak = max(self._page_occupancy_peak, occ)
        self._page_ticks += 1

    def record_retire(self, latency_s: float, reason: str) -> None:
        """A request finished (``reason``: eos | max_length | cache_full |
        timeout | cancelled | error)."""
        self.retired += 1
        self.latency_s.append(float(latency_s))
        self.finish_reasons[reason] = self.finish_reasons.get(reason, 0) + 1

    # admission-control counters are views over finish_reasons — one source
    # of truth, no parallel state to drift
    @property
    def timeouts(self) -> int:
        """Requests retired by queue-TTL or total-deadline expiry."""
        return self.finish_reasons.get("timeout", 0)

    @property
    def cancels(self) -> int:
        """Requests retired via ``cancel()``."""
        return self.finish_reasons.get("cancelled", 0)

    @property
    def callback_errors(self) -> int:
        """Requests retired because their ``on_token`` callback raised."""
        return self.finish_reasons.get("error", 0)

    def observe_tick(self, queue_depth: int, active_slots: int,
                     tick_s: Optional[float] = None) -> None:
        """Per-tick gauge sample from the engine's scheduler loop;
        ``tick_s`` is the tick's wall-clock (feeds the p50/p99 that make
        recovery/quarantine cost visible next to steady-state ticks)."""
        self.ticks += 1
        self.queue_depth = queue_depth
        self.active_slots = active_slots
        self._queue_depth_sum += queue_depth
        self._queue_depth_peak = max(self._queue_depth_peak, queue_depth)
        self._occupancy_sum += active_slots
        if tick_s is not None:
            self.tick_s.append(float(tick_s))

    def snapshot(self) -> Dict:
        """Aggregate view: counters, queue/occupancy stats, TTFT
        percentiles, decode tokens/s."""
        span = None
        if self._first_token_t is not None and self._last_token_t is not None:
            span = self._last_token_t - self._first_token_t
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "retired": self.retired,
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "cancels": self.cancels,
            "callback_errors": self.callback_errors,
            "tokens_generated": self.tokens_generated,
            "ticks": self.ticks,
            "queue_depth": self.queue_depth,
            "queue_depth_mean": (self._queue_depth_sum / self.ticks
                                 if self.ticks else 0.0),
            "queue_depth_peak": self._queue_depth_peak,
            "active_slots": self.active_slots,
            "slots": self.slots,
            "slot_occupancy_mean": (self._occupancy_sum / self.ticks / self.slots
                                    if self.ticks and self.slots else 0.0),
            "ttft_ms_mean": (float(np.mean(self.ttft_s)) * 1e3
                             if self.ttft_s else None),
            "ttft_ms_p50": (None if not self.ttft_s
                            else _pct(self.ttft_s, 50) * 1e3),
            "ttft_ms_p95": (None if not self.ttft_s
                            else _pct(self.ttft_s, 95) * 1e3),
            "queue_wait_ms_mean": (float(np.mean(self.queue_wait_s)) * 1e3
                                   if self.queue_wait_s else None),
            "latency_ms_mean": (float(np.mean(self.latency_s)) * 1e3
                                if self.latency_s else None),
            "decode_tokens_per_s": (self.tokens_generated / span
                                    if span and span > 0 else None),
            "finish_reasons": dict(self.finish_reasons),
            # paged-cache story: how much prefill the prefix trie saved
            # and how full the page pool ran (zeros on the slot path)
            "prefix_queries": self.prefix_queries,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": (self.prefix_hits / self.prefix_queries
                                if self.prefix_queries else 0.0),
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "prefill_tokens_saved_frac": (
                self.prefill_tokens_saved / self.prompt_tokens
                if self.prompt_tokens else 0.0),
            "pages_per_request_mean": (
                float(np.mean(self.pages_per_request))
                if self.pages_per_request else None),
            "pages_in_use": self.pages_in_use,
            "pages_total": self.pages_total,
            "page_occupancy_mean": (self._page_occupancy_sum
                                    / self._page_ticks
                                    if self._page_ticks else 0.0),
            "page_occupancy_peak": self._page_occupancy_peak,
            # crash-safety story: how often the engine recovered, what it
            # quarantined, what shutdown turned away, and what a tick costs
            "engine_recoveries": self.engine_recoveries,
            "poison_retired": self.poison_retired,
            "drain_rejects": self.drain_rejects,
            "tick_ms_p50": (None if not self.tick_s
                            else _pct(self.tick_s, 50) * 1e3),
            "tick_ms_p99": (None if not self.tick_s
                            else _pct(self.tick_s, 99) * 1e3),
        }

    def log_snapshot(self) -> None:
        """One structured log line through the framework logger."""
        from fleetx_tpu.utils.log import logger

        s = self.snapshot()
        logger.info(
            "serving: queue=%d active=%d/%d retired=%d/%d rejected=%d "
            "timeouts=%d cancels=%d tokens=%d "
            "occupancy=%.2f tok/s=%s ttft_ms_p50=%s",
            s["queue_depth"], s["active_slots"], s["slots"], s["retired"],
            s["submitted"], s["rejected"], s["timeouts"], s["cancels"],
            s["tokens_generated"], s["slot_occupancy_mean"],
            ("%.1f" % s["decode_tokens_per_s"]
             if s["decode_tokens_per_s"] else "-"),
            ("%.1f" % s["ttft_ms_p50"] if s["ttft_ms_p50"] else "-"),
        )
