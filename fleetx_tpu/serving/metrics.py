"""Serving observability: queue/slot/latency/throughput counters.

One :class:`ServingMetrics` instance rides a :class:`ServingEngine`; the
engine feeds it lifecycle events (submit/admit/first-token/retire) and a
per-tick gauge sample (queue depth, active slots). ``snapshot()`` returns
the aggregate dict the benches and tests consume; ``log_snapshot()``
surfaces the same line through ``utils/log.py`` (gate the cadence with
``FLEETX_SERVING_LOG_EVERY``).

Since the unified observability layer (docs/OBSERVABILITY.md) every
number here lives in :mod:`fleetx_tpu.obs.registry` instruments labeled
``engine="<n>"`` — the class is a thin façade that names the metrics
once and keeps the historical ``snapshot()``/attribute surface, while
``GET /metrics`` (``FLEETX_OBS_PORT``) exposes the same series as
Prometheus text. Latency/TTFT/tick distributions are bounded histogram
reservoirs (``FLEETX_OBS_RESERVOIR`` samples), which retired the
grow-forever ``ttft_s``/``queue_wait_s``/``latency_s``/
``pages_per_request`` lists a long-lived replica used to accumulate:
means stay exact (count/sum), percentiles describe the recent window.

TTFT here is end-to-end: submit → the request's first token is on the
host (queue wait + prefill + the device sync), which is what a caller
actually observes — first requests include compile time, so warm up
before reading latencies as steady-state.
"""

from __future__ import annotations

import itertools
import time
import weakref
from typing import Dict, Optional

from fleetx_tpu.obs.registry import MetricsRegistry, get_registry

__all__ = ["ServingMetrics"]


def _drop_series(owned) -> None:
    """weakref.finalize target: remove every registry series a
    ServingMetrics instance owned (its ``engine=<n>`` label is unique,
    so a process that cycles engines would otherwise accumulate
    dead-engine series in /metrics forever)."""
    for family, labels in owned:
        family.remove(**labels)


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else v * 1e3


class ServingMetrics:
    """Counters + gauges for one serving engine (see module docstring)."""

    _labels = itertools.count()

    def __init__(self, slots: int = 0,
                 registry: Optional[MetricsRegistry] = None):
        reg = registry or get_registry()
        self.registry = reg
        self.engine_label = str(next(self._labels))
        lab = {"engine": self.engine_label}
        # (family, labels) of every series this instance creates; a
        # weakref finalizer removes them when the instance dies, so the
        # registry's memory stays bounded across engine restarts. A plain
        # list captured by closure — the finalizer must not pin self.
        self._owned = owned = []

        def child(fam):
            owned.append((fam, dict(lab)))
            return fam.labels(**lab)

        def counter(name, help):
            return child(reg.counter(name, help, ("engine",)))

        def gauge(name, help):
            return child(reg.gauge(name, help, ("engine",)))

        def hist(name, help):
            return child(reg.histogram(name, help, ("engine",)))

        self.slots = slots
        self._c_submitted = counter(
            "fleetx_serving_submitted_total",
            "Requests that entered the admission queue")
        self._c_admitted = counter(
            "fleetx_serving_admitted_total",
            "Requests that won a decode lane (prefill ran)")
        self._retired_family = reg.counter(
            "fleetx_serving_retired_total",
            "Requests retired, by finish_reason",
            ("engine", "reason"))
        self._c_rejected = counter(
            "fleetx_serving_rejected_total",
            "Submits refused by admission control (queue full)")
        self._c_drain_rejects = counter(
            "fleetx_serving_drain_rejects_total",
            "Submits refused because the engine was shutting down")
        self._c_tokens = counter(
            "fleetx_serving_tokens_total",
            "Decode tokens that reached the host")
        self._c_ticks = counter(
            "fleetx_serving_ticks_total",
            "Scheduler ticks executed")
        # crash-safety counters (docs/RESILIENCE.md serving-recovery):
        # recoveries = replay-recovery passes the engine ran, poison =
        # requests quarantined by bisection/replay
        self._c_recoveries = counter(
            "fleetx_serving_engine_recoveries_total",
            "Replay-recovery passes (device state rebuilt from host truth)")
        self._c_poison = counter(
            "fleetx_serving_poison_retired_total",
            "Requests quarantined as poison (bisection or replay failure)")
        # paged-cache counters (zero on the slot path so the snapshot
        # schema is stable across modes)
        self._c_prefix_queries = counter(
            "fleetx_serving_prefix_queries_total",
            "Paged admissions that consulted the prefix trie")
        self._c_prefix_hits = counter(
            "fleetx_serving_prefix_hits_total",
            "Paged admissions that reused shared prefix pages")
        self._c_prefill_saved = counter(
            "fleetx_serving_prefill_tokens_saved_total",
            "Prompt tokens whose prefill the prefix cache skipped")
        self._c_prompt_tokens = counter(
            "fleetx_serving_prompt_tokens_total",
            "Prompt tokens across admitted paged requests")
        # chunked-prefill + host-spill-tier story (docs/SERVING.md):
        # how long ticks stall on prefill work, how many chunks ran, and
        # what the two-level page cache moved between HBM and host DRAM
        self._c_prefill_chunks = counter(
            "fleetx_serving_prefill_chunks_total",
            "Chunked-prefill device calls executed (one per tick max)")
        self._c_host_spilled = counter(
            "fleetx_serving_host_spilled_pages_total",
            "Warm KV pages spilled to the host-DRAM tier on eviction")
        self._c_host_revived = counter(
            "fleetx_serving_host_revived_pages_total",
            "Spilled pages revived into device pages on a prefix match")
        self._c_host_evicted = counter(
            "fleetx_serving_host_evicted_pages_total",
            "Host-tier entries dropped under the byte budget (LRU)")
        self._host_synced = (0, 0, 0)  # last (spilled, revived, evicted)
        # disaggregated prefill/decode (docs/SERVING.md): the handoff
        # counters — pages/bytes a prefill-role replica exported, pages a
        # decode-role replica revived from a remote ship — plus the
        # shared disk tier's traffic and the per-phase load signals
        self._c_kv_shipped = counter(
            "fleetx_serving_kv_pages_shipped_total",
            "KV pages exported to a decode-role replica (export_kv)")
        self._c_kv_bytes_shipped = counter(
            "fleetx_serving_kv_bytes_shipped_total",
            "Wire-format bytes of exported KV page payloads")
        self._c_kv_revived_remote = counter(
            "fleetx_serving_kv_pages_revived_remote_total",
            "Shipped KV pages revived into this replica's pool "
            "(submit(kv_payloads=...), no re-prefill)")
        self._g_disk_bytes = gauge(
            "fleetx_serving_disk_cache_bytes",
            "Bytes of wire-format KV pages resident in the shared "
            "disk tier (FLEETX_SERVING_DISK_CACHE_DIR)")
        self._c_disk_hits = counter(
            "fleetx_serving_disk_cache_hits_total",
            "Disk-tier reads that revived a page (any replica wrote it)")
        self._c_disk_misses = counter(
            "fleetx_serving_disk_cache_misses_total",
            "Disk-tier probes that found no stored page")
        self._disk_synced = (0, 0)  # last (hits, misses)
        self._g_queue_tokens = gauge(
            "fleetx_serving_prefill_queue_tokens",
            "Prompt tokens queued or mid-chunked-prefill — the load "
            "signal the router prices a prefill-role replica by")
        # info-style role family: 1 at the engine's serving role, so one
        # scrape says which pool each replica belongs to
        self._role_family = reg.gauge(
            "fleetx_serving_role",
            "1 at the engine's serving role (prefill | decode | both)",
            ("engine", "role"))
        self.role = "both"
        # speculative decoding (docs/SERVING.md): proposer/verifier
        # throughput — acceptance rate prices the proposer, tokens-per-
        # tick is the decode multiplier the whole feature exists for
        self._c_spec_proposed = counter(
            "fleetx_serving_spec_proposed_tokens_total",
            "Draft tokens proposed to speculative verification")
        self._c_spec_accepted = counter(
            "fleetx_serving_spec_accepted_tokens_total",
            "Proposed draft tokens the target model accepted")
        self._g_spec_rate = gauge(
            "fleetx_serving_spec_acceptance_rate",
            "Lifetime accepted/proposed draft-token ratio")
        self._h_spec_tokens = hist(
            "fleetx_serving_spec_tokens_per_tick",
            "Tokens emitted per active request per speculative tick "
            "(accepted drafts + the correction/bonus token)")
        self._g_queue_depth = gauge(
            "fleetx_serving_queue_depth",
            "Requests currently waiting for a decode lane")
        self._g_active_slots = gauge(
            "fleetx_serving_active_slots",
            "Decode lanes currently occupied")
        self._g_slots = gauge(
            "fleetx_serving_slots",
            "Configured decode lanes of this engine")
        self._g_slots.set(slots)
        self._g_pages_in_use = gauge(
            "fleetx_serving_pages_in_use",
            "KV pages currently allocated (paged mode)")
        self._g_pages_total = gauge(
            "fleetx_serving_pages_total",
            "Usable KV pages in the shared pool (paged mode)")
        self._g_host_bytes = gauge(
            "fleetx_serving_host_cache_bytes",
            "Bytes of spilled KV pages resident in the host-DRAM tier")
        self._g_host_pages = gauge(
            "fleetx_serving_host_cache_pages",
            "Spilled KV pages resident in the host-DRAM tier")
        # mesh-sharded serving (docs/SERVING.md "Mesh-sharded serving"):
        # how many devices this engine's decode tick spans — the router
        # reads it to price a replica's capacity (1 = unmeshed)
        self._g_mesh_devices = gauge(
            "fleetx_serving_mesh_devices",
            "Devices the engine's jitted decode tick runs across "
            "(1 = single-device engine)")
        self._g_mesh_devices.set(1)
        self.mesh_desc = "-"
        # quantized-serving config (docs/QUANTIZATION.md): the info-style
        # family carries the active precision pair as labels — plus the
        # mesh shape, so one scrape says what precision runs on what
        # device slice; the bytes gauges make the HBM win scrapeable
        # next to tokens/s
        self._quant_family = reg.gauge(
            "fleetx_serving_quant_config",
            "1 at the engine's active (kv_dtype, weight_dtype, mesh) tuple",
            ("engine", "kv_dtype", "weight_dtype", "mesh"))
        self._g_kv_bytes = gauge(
            "fleetx_serving_kv_bytes_per_token",
            "KV-cache bytes one cached token costs across all layers "
            "(per-vector scales included at int8)")
        self._g_weight_bytes = gauge(
            "fleetx_serving_weight_bytes",
            "Bytes of servable params resident in HBM "
            "(int8 values + scales when weight-quantized)")
        self._g_kv_cache_bytes = gauge(
            "fleetx_serving_kv_cache_bytes",
            "Device bytes of the whole decode cache tree, measured from "
            "its actual leaves (values + scale leaves)")
        self.kv_dtype = "bf16"
        self.weight_dtype = "bf16"
        self._h_ttft = hist(
            "fleetx_serving_ttft_seconds",
            "Submit-to-first-token latency (end-to-end, host observed)")
        self._h_queue_wait = hist(
            "fleetx_serving_queue_wait_seconds",
            "Time spent waiting in the admission queue")
        self._h_latency = hist(
            "fleetx_serving_request_latency_seconds",
            "Submit-to-retire request latency")
        # per-tick wall-clock feeds the p50/p99 that make recovery/
        # quarantine cost visible next to steady-state ticks
        self._h_tick = hist(
            "fleetx_serving_tick_seconds",
            "Scheduler tick wall-clock")
        self._h_queue_depth = hist(
            "fleetx_serving_queue_depth_per_tick",
            "Queue depth sampled once per tick (mean/peak feed snapshot)")
        self._h_active = hist(
            "fleetx_serving_active_slots_per_tick",
            "Occupied lanes sampled once per tick")
        self._h_page_occ = hist(
            "fleetx_serving_page_occupancy",
            "Page-pool occupancy fraction sampled once per tick")
        self._h_pages_per_req = hist(
            "fleetx_serving_pages_per_request",
            "Fresh (non-shared) pages claimed per admitted paged request")
        # how long a tick's decode was stalled by prefill work — under
        # chunking this is bounded by ~one chunk-sized call (the claim
        # tools/bench_serving.py's chunked record prices)
        self._h_prefill_stall = hist(
            "fleetx_serving_prefill_stall_ms",
            "Milliseconds a tick spent on prefill work (admissions + "
            "chunks) before its batched decode ran")
        # dynamic-batching engines (serving/batch_engine.py): coalesced
        # forwards and how full each one ran — the KV-free analogue of
        # active-slot occupancy
        self._c_batched_forwards = counter(
            "fleetx_serving_batched_forwards_total",
            "Coalesced batched forwards run by a KV-free engine")
        self._h_batch_occ = hist(
            "fleetx_serving_batch_occupancy",
            "Fraction of the coalescing window filled per batched forward")
        self._reasons: Dict[str, object] = {}  # reason -> counter child
        self._first_token_t: Optional[float] = None
        self._last_token_t: Optional[float] = None
        weakref.finalize(self, _drop_series, owned)

    # ------------------------------------------------- lifecycle recording
    def record_submit(self) -> None:
        """A request entered the admission queue."""
        self._c_submitted.inc()

    def record_admit(self, queue_wait_s: float) -> None:
        """A request won a slot after waiting ``queue_wait_s``."""
        self._c_admitted.inc()
        self._h_queue_wait.observe(queue_wait_s)

    def record_first_token(self, ttft_s: float) -> None:
        """First token of a request reached the host (end-to-end TTFT)."""
        self._h_ttft.observe(ttft_s)

    def record_tokens(self, n: int) -> None:
        """``n`` decode tokens reached the host this tick."""
        now = time.perf_counter()
        if self._first_token_t is None:
            self._first_token_t = now
        self._last_token_t = now
        self._c_tokens.inc(n)

    def record_reject(self) -> None:
        """A submit was refused by admission control (queue full)."""
        self._c_rejected.inc()

    def record_recovery(self) -> None:
        """The engine ran one replay-recovery pass (device state rebuilt
        and every active request re-prefilled from its host history)."""
        self._c_recoveries.inc()

    def record_poison(self) -> None:
        """A poison request was quarantined (bisection or replay failure)
        and retired with ``finish_reason="error"``."""
        self._c_poison.inc()

    def record_drain_reject(self) -> None:
        """A submit was refused because the engine is shutting down."""
        self._c_drain_rejects.inc()

    def record_batched_forward(self, batch: int, capacity: int) -> None:
        """A KV-free engine ran one coalesced forward over ``batch``
        requests with room for ``capacity``."""
        self._c_batched_forwards.inc()
        self._h_batch_occ.observe(batch / max(capacity, 1))

    def record_prefix(self, shared_tokens: int, prompt_tokens: int,
                      pages: int) -> None:
        """One paged admission: ``shared_tokens`` of the prompt came from
        the prefix cache (their prefill was skipped), ``pages`` is the
        FRESH pages the request claimed (trie-shared pages excluded —
        they cost nothing, which is the point)."""
        self._c_prefix_queries.inc()
        if shared_tokens > 0:
            self._c_prefix_hits.inc()
        self._c_prefill_saved.inc(int(shared_tokens))
        self._c_prompt_tokens.inc(int(prompt_tokens))
        self._h_pages_per_req.observe(int(pages))

    def set_mesh(self, devices: int, desc: str = "-") -> None:
        """Publish the engine's mesh footprint: ``devices`` the decode
        tick spans (1 = unmeshed) and a short shape string (e.g.
        ``"mp2"``, ``"fsdp2xmp2"``; ``"-"`` unmeshed) that also labels
        the quant-config info gauge."""
        self.mesh_desc = desc
        self._g_mesh_devices.set(int(devices))

    def set_quant_config(self, kv_dtype: str, weight_dtype: str,
                         kv_bytes_per_token: int, weight_bytes: int,
                         kv_cache_bytes: int = 0) -> None:
        """Publish the engine's precision config: the (kv_dtype,
        weight_dtype, mesh) info labels plus the bytes-per-token /
        param-bytes / cache-tree gauges the HBM story is read from
        (docs/QUANTIZATION.md; bytes are PER DEVICE under a mesh —
        docs/SERVING.md "Mesh-sharded serving"). Call :meth:`set_mesh`
        first on a meshed engine so the label is current."""
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        labels = {"engine": self.engine_label, "kv_dtype": kv_dtype,
                  "weight_dtype": weight_dtype, "mesh": self.mesh_desc}
        self._owned.append((self._quant_family, dict(labels)))
        self._quant_family.labels(**labels).set(1)
        self._g_kv_bytes.set(int(kv_bytes_per_token))
        self._g_weight_bytes.set(int(weight_bytes))
        self._g_kv_cache_bytes.set(int(kv_cache_bytes))

    def observe_prefill_stall(self, stall_s: float) -> None:
        """One tick spent ``stall_s`` seconds on prefill work (whole
        admissions or one chunk) before its decode call."""
        self._h_prefill_stall.observe(stall_s * 1e3)

    def record_prefill_chunk(self, tokens: int) -> None:
        """One chunked-prefill device call wrote ``tokens`` prompt
        tokens (the count rides the counter; per-chunk size is static)."""
        del tokens  # chunk size is a config constant; count is the signal
        self._c_prefill_chunks.inc()

    def observe_host_tier(self, store) -> None:
        """Per-tick sync from a :class:`HostPageStore`: gauges track its
        current bytes/entries, counters advance by the store's lifetime
        deltas since the last sync (registry counters only increment)."""
        self._g_host_bytes.set(store.nbytes)
        self._g_host_pages.set(len(store))
        now = (store.spilled_pages, store.revived_pages,
               store.evicted_pages)
        last = self._host_synced
        for child, delta in zip(
                (self._c_host_spilled, self._c_host_revived,
                 self._c_host_evicted),
                (now[0] - last[0], now[1] - last[1], now[2] - last[2])):
            if delta > 0:
                child.inc(delta)
        self._host_synced = now

    def set_role(self, role: str) -> None:
        """Publish the engine's serving role (``prefill`` | ``decode`` |
        ``both``) — the info-style label the router and a fleet scrape
        read replica specialization from."""
        self.role = role
        labels = {"engine": self.engine_label, "role": role}
        self._owned.append((self._role_family, dict(labels)))
        self._role_family.labels(**labels).set(1)

    def record_kv_shipped(self, pages: int, nbytes: int) -> None:
        """One successful ``export_kv``: ``pages`` page payloads,
        ``nbytes`` total wire-format bytes, left this replica for a
        decode-role peer."""
        self._c_kv_shipped.inc(int(pages))
        self._c_kv_bytes_shipped.inc(int(nbytes))

    def record_kv_revived_remote(self, pages: int) -> None:
        """One ``submit(kv_payloads=...)`` admission revived ``pages``
        shipped pages into this replica's pool (their prefill skipped —
        the whole point of the handoff)."""
        self._c_kv_revived_remote.inc(int(pages))

    def observe_queue_tokens(self, tokens: int) -> None:
        """Per-tick sample of queued + mid-chunk prompt tokens (the
        prefill-phase load signal)."""
        self._g_queue_tokens.set(int(tokens))

    def observe_disk_tier(self, store) -> None:
        """Per-tick sync from a :class:`DiskPageStore`: the bytes gauge
        tracks the shared directory's current residency (every
        replica's writes included), hit/miss counters advance by this
        instance's lifetime deltas (registry counters only increment)."""
        self._g_disk_bytes.set(store.nbytes)
        now = (store.hits, store.misses)
        last = self._disk_synced
        for child, delta in zip((self._c_disk_hits, self._c_disk_misses),
                                (now[0] - last[0], now[1] - last[1])):
            if delta > 0:
                child.inc(delta)
        self._disk_synced = now

    def record_spec(self, proposed: int, accepted: int,
                    emitted_rows) -> None:
        """One speculative tick: ``proposed``/``accepted`` draft tokens
        across the batch, ``emitted_rows`` the per-request emitted-token
        counts (each feeds the tokens-per-tick histogram)."""
        if proposed > 0:
            self._c_spec_proposed.inc(proposed)
        if accepted > 0:
            self._c_spec_accepted.inc(accepted)
        total = int(self._c_spec_proposed.value)
        self._g_spec_rate.set(
            int(self._c_spec_accepted.value) / total if total else 0.0)
        for n in emitted_rows:
            self._h_spec_tokens.observe(int(n))

    def observe_pages(self, pages_in_use: int, pages_total: int) -> None:
        """Per-tick page-pool gauge sample (paged mode only)."""
        self._g_pages_in_use.set(pages_in_use)
        self._g_pages_total.set(pages_total)
        self._h_page_occ.observe(
            pages_in_use / pages_total if pages_total else 0.0)

    def record_retire(self, latency_s: float, reason: str) -> None:
        """A request finished (``reason``: eos | max_length | cache_full |
        timeout | cancelled | error | shutdown)."""
        child = self._reasons.get(reason)
        if child is None:
            labels = {"engine": self.engine_label, "reason": reason}
            self._owned.append((self._retired_family, labels))
            child = self._reasons[reason] = self._retired_family.labels(
                **labels)
        child.inc()
        self._h_latency.observe(latency_s)

    def observe_tick(self, queue_depth: int, active_slots: int,
                     tick_s: Optional[float] = None) -> None:
        """Per-tick gauge sample from the engine's scheduler loop;
        ``tick_s`` is the tick's wall-clock (feeds the p50/p99 that make
        recovery/quarantine cost visible next to steady-state ticks)."""
        self._c_ticks.inc()
        self._g_queue_depth.set(queue_depth)
        self._g_active_slots.set(active_slots)
        self._h_queue_depth.observe(queue_depth)
        self._h_active.observe(active_slots)
        if tick_s is not None:
            self._h_tick.observe(tick_s)

    # ------------------------------------------------- attribute surface
    # (historic int attributes, now views over the registry children —
    # one source of truth, no parallel state to drift)
    @property
    def submitted(self) -> int:
        """Requests submitted."""
        return int(self._c_submitted.value)

    @property
    def admitted(self) -> int:
        """Requests admitted into a decode lane."""
        return int(self._c_admitted.value)

    @property
    def retired(self) -> int:
        """Requests retired, any finish_reason."""
        return sum(int(c.value) for c in self._reasons.values())

    @property
    def rejected(self) -> int:
        """Submits rejected by the bounded queue."""
        return int(self._c_rejected.value)

    @property
    def tokens_generated(self) -> int:
        """Decode tokens that reached the host."""
        return int(self._c_tokens.value)

    @property
    def ticks(self) -> int:
        """Scheduler ticks executed."""
        return int(self._c_ticks.value)

    @property
    def finish_reasons(self) -> Dict[str, int]:
        """``{finish_reason: count}`` over this engine's retirements."""
        return {r: int(c.value) for r, c in self._reasons.items()
                if int(c.value)}

    @property
    def engine_recoveries(self) -> int:
        """Replay-recovery passes this engine ran."""
        return int(self._c_recoveries.value)

    @property
    def poison_retired(self) -> int:
        """Requests quarantined as poison."""
        return int(self._c_poison.value)

    @property
    def drain_rejects(self) -> int:
        """Submits refused during shutdown drain."""
        return int(self._c_drain_rejects.value)

    @property
    def timeouts(self) -> int:
        """Requests retired by queue-TTL or total-deadline expiry."""
        return self.finish_reasons.get("timeout", 0)

    @property
    def cancels(self) -> int:
        """Requests retired via ``cancel()``."""
        return self.finish_reasons.get("cancelled", 0)

    @property
    def callback_errors(self) -> int:
        """Requests retired because their ``on_token`` callback raised."""
        return self.finish_reasons.get("error", 0)

    @property
    def prefix_queries(self) -> int:
        """Paged admissions that consulted the prefix trie."""
        return int(self._c_prefix_queries.value)

    @property
    def prefix_hits(self) -> int:
        """Paged admissions that reused shared pages."""
        return int(self._c_prefix_hits.value)

    @property
    def prefill_tokens_saved(self) -> int:
        """Prompt tokens whose prefill the prefix cache skipped."""
        return int(self._c_prefill_saved.value)

    @property
    def prompt_tokens(self) -> int:
        """Prompt tokens across admitted paged requests."""
        return int(self._c_prompt_tokens.value)

    @property
    def prefill_chunks(self) -> int:
        """Chunked-prefill device calls executed."""
        return int(self._c_prefill_chunks.value)

    @property
    def host_spilled_pages(self) -> int:
        """Warm pages spilled to the host tier."""
        return int(self._c_host_spilled.value)

    @property
    def host_revived_pages(self) -> int:
        """Spilled pages revived on a prefix match."""
        return int(self._c_host_revived.value)

    @property
    def host_evicted_pages(self) -> int:
        """Host-tier entries dropped under the byte budget."""
        return int(self._c_host_evicted.value)

    @property
    def kv_pages_shipped(self) -> int:
        """KV pages exported to decode-role replicas."""
        return int(self._c_kv_shipped.value)

    @property
    def kv_bytes_shipped(self) -> int:
        """Wire-format bytes of exported KV page payloads."""
        return int(self._c_kv_bytes_shipped.value)

    @property
    def kv_pages_revived_remote(self) -> int:
        """Shipped pages revived into this replica's pool."""
        return int(self._c_kv_revived_remote.value)

    @property
    def disk_cache_hits(self) -> int:
        """Disk-tier reads that revived a page."""
        return int(self._c_disk_hits.value)

    @property
    def disk_cache_misses(self) -> int:
        """Disk-tier probes that found nothing."""
        return int(self._c_disk_misses.value)

    @property
    def spec_proposed_tokens(self) -> int:
        """Draft tokens proposed to speculative verification."""
        return int(self._c_spec_proposed.value)

    @property
    def spec_accepted_tokens(self) -> int:
        """Proposed draft tokens the target model accepted."""
        return int(self._c_spec_accepted.value)

    @property
    def queue_depth(self) -> int:
        """Last sampled queue depth."""
        return int(self._g_queue_depth.value)

    @property
    def active_slots(self) -> int:
        """Last sampled occupied-lane count."""
        return int(self._g_active_slots.value)

    @property
    def pages_in_use(self) -> int:
        """Last sampled allocated-page count (paged mode)."""
        return int(self._g_pages_in_use.value)

    @property
    def pages_total(self) -> int:
        """Last sampled usable-pool size (paged mode)."""
        return int(self._g_pages_total.value)

    # bounded-reservoir views (regression-tested: a 10k-retire loop must
    # hold these at the FLEETX_OBS_RESERVOIR cap, not 10k entries)
    @property
    def ttft_s(self):
        """TTFT reservoir (newest ``FLEETX_OBS_RESERVOIR`` samples)."""
        return self._h_ttft.reservoir

    @property
    def queue_wait_s(self):
        """Queue-wait reservoir."""
        return self._h_queue_wait.reservoir

    @property
    def latency_s(self):
        """Request-latency reservoir."""
        return self._h_latency.reservoir

    @property
    def tick_s(self):
        """Tick wall-clock reservoir."""
        return self._h_tick.reservoir

    @property
    def pages_per_request(self):
        """Fresh-pages-per-request reservoir."""
        return self._h_pages_per_req.reservoir

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict:
        """Aggregate view: counters, queue/occupancy stats, TTFT
        percentiles, decode tokens/s."""
        span = None
        if self._first_token_t is not None and self._last_token_t is not None:
            span = self._last_token_t - self._first_token_t
        ticks = self.ticks
        ttft_p50, ttft_p95 = self._h_ttft.quantiles((50, 95))
        tick_p50, tick_p99 = self._h_tick.quantiles((50, 99))
        stall_p50, stall_p99 = self._h_prefill_stall.quantiles((50, 99))
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "retired": self.retired,
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "cancels": self.cancels,
            "callback_errors": self.callback_errors,
            "tokens_generated": self.tokens_generated,
            "ticks": ticks,
            "queue_depth": self.queue_depth,
            "queue_depth_mean": (self._h_queue_depth.sum / ticks
                                 if ticks else 0.0),
            "queue_depth_peak": int(self._h_queue_depth.max or 0),
            "active_slots": self.active_slots,
            "slots": self.slots,
            "slot_occupancy_mean": (self._h_active.sum / ticks / self.slots
                                    if ticks and self.slots else 0.0),
            "ttft_ms_mean": _ms(self._h_ttft.mean),
            "ttft_ms_p50": _ms(ttft_p50),
            "ttft_ms_p95": _ms(ttft_p95),
            "queue_wait_ms_mean": _ms(self._h_queue_wait.mean),
            "latency_ms_mean": _ms(self._h_latency.mean),
            "decode_tokens_per_s": (self.tokens_generated / span
                                    if span and span > 0 else None),
            "finish_reasons": self.finish_reasons,
            # paged-cache story: how much prefill the prefix trie saved
            # and how full the page pool ran (zeros on the slot path)
            "prefix_queries": self.prefix_queries,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": (self.prefix_hits / self.prefix_queries
                                if self.prefix_queries else 0.0),
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "prefill_tokens_saved_frac": (
                self.prefill_tokens_saved / self.prompt_tokens
                if self.prompt_tokens else 0.0),
            "pages_per_request_mean": self._h_pages_per_req.mean,
            "pages_in_use": self.pages_in_use,
            "pages_total": self.pages_total,
            # chunked-prefill + host-tier story (docs/SERVING.md): decode
            # stall bounded by one chunk, prefix hits sustained past the
            # device pool via the host-DRAM spill tier
            "prefill_chunks": self.prefill_chunks,
            "prefill_stall_ms_p50": stall_p50,
            "prefill_stall_ms_p99": stall_p99,
            "prefill_stall_ms_max": self._h_prefill_stall.max,
            "host_spilled_pages": self.host_spilled_pages,
            "host_revived_pages": self.host_revived_pages,
            "host_evicted_pages": self.host_evicted_pages,
            "host_cache_bytes": int(self._g_host_bytes.value),
            "host_cache_pages": int(self._g_host_pages.value),
            # disaggregation story (docs/SERVING.md "Disaggregated
            # prefill/decode"): what this replica shipped out / revived
            # in, its role in the fleet, the prefill-phase load signal,
            # and the shared disk tier's traffic
            "role": self.role,
            "kv_pages_shipped": self.kv_pages_shipped,
            "kv_bytes_shipped": self.kv_bytes_shipped,
            "kv_pages_revived_remote": self.kv_pages_revived_remote,
            "prefill_queue_tokens": int(self._g_queue_tokens.value),
            "disk_cache_bytes": int(self._g_disk_bytes.value),
            "disk_cache_hits": self.disk_cache_hits,
            "disk_cache_misses": self.disk_cache_misses,
            "page_occupancy_mean": (self._h_page_occ.mean or 0.0),
            "page_occupancy_peak": (self._h_page_occ.max or 0.0),
            # precision story (docs/QUANTIZATION.md): what the decode path
            # stores K/V and weights as, and what that costs in HBM
            "kv_dtype": self.kv_dtype,
            "weight_dtype": self.weight_dtype,
            "kv_bytes_per_token": int(self._g_kv_bytes.value),
            "weight_bytes": int(self._g_weight_bytes.value),
            "kv_cache_bytes": int(self._g_kv_cache_bytes.value),
            # mesh story (docs/SERVING.md "Mesh-sharded serving"): how
            # many devices the decode tick spans; the bytes gauges above
            # are PER DEVICE, so they shrink as the mesh grows
            "mesh_devices": int(self._g_mesh_devices.value),
            "mesh": self.mesh_desc,
            # speculative-decoding story (docs/SERVING.md): what the
            # proposer offered, what verification kept, and the
            # resulting decode multiplier (1.0 mean = nothing accepted)
            "spec_proposed_tokens": self.spec_proposed_tokens,
            "spec_accepted_tokens": self.spec_accepted_tokens,
            "spec_acceptance_rate": float(self._g_spec_rate.value),
            "spec_tokens_per_tick_mean": self._h_spec_tokens.mean,
            # crash-safety story: how often the engine recovered, what it
            # quarantined, what shutdown turned away, and what a tick costs
            "engine_recoveries": self.engine_recoveries,
            "poison_retired": self.poison_retired,
            "drain_rejects": self.drain_rejects,
            "tick_ms_p50": _ms(tick_p50),
            "tick_ms_p99": _ms(tick_p99),
        }

    def log_snapshot(self) -> None:
        """One structured log line through the framework logger."""
        from fleetx_tpu.utils.log import logger

        s = self.snapshot()
        logger.info(
            "serving: queue=%d active=%d/%d retired=%d/%d rejected=%d "
            "timeouts=%d cancels=%d tokens=%d "
            "occupancy=%.2f tok/s=%s ttft_ms_p50=%s",
            s["queue_depth"], s["active_slots"], s["slots"], s["retired"],
            s["submitted"], s["rejected"], s["timeouts"], s["cancels"],
            s["tokens_generated"], s["slot_occupancy_mean"],
            ("%.1f" % s["decode_tokens_per_s"]
             if s["decode_tokens_per_s"] else "-"),
            ("%.1f" % s["ttft_ms_p50"] if s["ttft_ms_p50"] else "-"),
        )
