"""Ring-attention context parallelism over a ``cp`` mesh axis.

The reference's only long-sequence mechanism is Megatron sequence parallel
tied to the TP degree (/root/reference/ppfleetx/models/language_model/gpt/
dygraph/sequence_parallel_utils.py:40-395) — activations are sharded
[s/n, b, h] *between* attention/FFN but every rank still materializes the
full sequence inside attention. This module goes beyond that with true
context parallelism: the sequence stays sharded *through* attention and
KV blocks rotate around the ``cp`` ring with ``lax.ppermute`` while each
device accumulates its queries' output with an online (flash-style)
softmax. Memory per device is O(s/cp) activations and O(s/cp) KV at a
time; the [s, s] score matrix never exists.

This is the TPU-native form of Ring Attention (blockwise parallel
transformers): the permute collective rides the ICI ring, and each hop
overlaps with the local attention block's compute under XLA async
collectives.

Causality is handled at block granularity with a zig-zag layout: device i
holds query/key blocks (i, 2*cp-1-i) of 2*cp equal slices, so every device
owns one "early" and one "late" block and the causal triangle's work is
balanced across the ring (a plain contiguous split leaves rank 0 almost
idle). `zigzag_split`/`zigzag_merge` convert between contiguous and
zig-zag order on the host or with pure reshapes under jit.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fleetx_tpu.ops.attention import NEG_INF

__all__ = [
    "ring_attention",
    "ring_self_attention",
    "zigzag_split",
    "zigzag_merge",
]


def _block_scores(q, k, scale):
    # q [b, sq, h, d] x k [b, sk, h, d] -> [b, h, sq, sk], fp32 accumulate.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    return s * scale


def _online_update(acc, m, l, scores, v):
    """One flash-attention accumulation step.

    acc [b, h, sq, d] fp32 running numerator, m [b, h, sq] running max,
    l [b, h, sq] running denominator; scores [b, h, sq, sk] fp32 (already
    masked); v [b, sk, h, d].
    """
    m_new = jnp.maximum(m, scores.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    acc_new = acc * corr[..., None] + pv
    return acc_new, m_new, l_new


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
) -> jax.Array:
    """Per-device body — runs inside shard_map; sequence axis sharded over
    ``axis_name``. q, k, v: [b, 2, s_blk, h, d] with the two zig-zag blocks
    stacked on dim 1 (block 0 = "early" slice, block 1 = "late" slice).
    """
    cp = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, two, s_blk, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))

    # Global block ids of this device's two zig-zag slices.
    my_blocks = jnp.stack([me, 2 * cp - 1 - me])  # [2]

    acc = jnp.zeros((2, b, h, s_blk, d), jnp.float32)
    m = jnp.full((2, b, h, s_blk), NEG_INF, jnp.float32)
    l = jnp.zeros((2, b, h, s_blk), jnp.float32)

    def accumulate(t, acc, m, l, k_cur, v_cur):
        # k_cur/v_cur originated on rank (me - t) mod cp.
        src = (me - t) % cp
        kv_blocks = jnp.stack([src, 2 * cp - 1 - src])  # [2]

        def one_pair(qi, acc_i, m_i, l_i):
            """Attend q block qi (global id my_blocks[qi]) over both kv blocks."""
            qb = q[:, qi]
            for kj in range(2):
                kb, vb = k_cur[:, kj], v_cur[:, kj]
                scores = _block_scores(qb, kb, scale)
                if causal:
                    q_pos = my_blocks[qi] * s_blk + jnp.arange(s_blk)[:, None]
                    k_pos = kv_blocks[kj] * s_blk + jnp.arange(s_blk)[None, :]
                    scores = scores + jnp.where(q_pos >= k_pos, 0.0, NEG_INF)
                acc_i, m_i, l_i = _online_update(acc_i, m_i, l_i, scores, vb)
            return acc_i, m_i, l_i

        new_acc, new_m, new_l = [], [], []
        for qi in range(2):
            a, mm, ll = one_pair(qi, acc[qi], m[qi], l[qi])
            new_acc.append(a)
            new_m.append(mm)
            new_l.append(ll)
        return jnp.stack(new_acc), jnp.stack(new_m), jnp.stack(new_l)

    def step(t, carry):
        acc, m, l, k_cur, v_cur = carry
        acc, m, l = accumulate(t, acc, m, l, k_cur, v_cur)
        # Rotate KV around the ring: rank r hands its buffer to r+1.
        perm = [(r, (r + 1) % cp) for r in range(cp)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    # cp-1 rotate-and-accumulate steps, then a peeled final accumulate so the
    # last (unused) KV rotation never hits the ICI.
    acc, m, l, k_last, v_last = lax.fori_loop(0, cp - 1, step, (acc, m, l, k, v))
    acc, m, l = accumulate(cp - 1, acc, m, l, k_last, v_last)
    # l is 0 only if every block was fully masked — impossible for causal
    # self-attention (the diagonal block always attends), so divide directly.
    out = acc / l[..., None]  # [2, b, h, s_blk, d]
    out = jnp.moveaxis(out, 2, 3)  # [2, b, s_blk, h, d]
    return out.transpose(1, 0, 2, 3, 4).astype(q.dtype)  # [b, 2, s_blk, h, d]


def zigzag_split(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Contiguous -> zig-zag sequence order. Shape is unchanged; only the
    order along ``axis`` changes: the sequence is cut into 2*cp equal blocks
    and reordered to [b0, b_{2cp-1}, b1, b_{2cp-2}, ...], so an even split
    over cp devices gives device i its pair (b_i, b_{2cp-1-i}) contiguously
    — one "early" and one "late" block, balancing the causal triangle.
    """
    return _permute_blocks(x, cp, axis, invert=False)


def zigzag_merge(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Inverse of zigzag_split."""
    return _permute_blocks(x, cp, axis, invert=True)


def _zigzag_order(cp: int):
    order = []
    for i in range(cp):
        order += [i, 2 * cp - 1 - i]
    return order


def _permute_blocks(x: jax.Array, cp: int, axis: int, invert: bool) -> jax.Array:
    s = x.shape[axis]
    assert s % (2 * cp) == 0, f"seq {s} not divisible by 2*cp={2 * cp}"
    s_blk = s // (2 * cp)
    order = _zigzag_order(cp)
    if invert:
        inv = [0] * (2 * cp)
        for pos, blk in enumerate(order):
            inv[blk] = pos
        order = inv
    x = jnp.moveaxis(x, axis, 0)
    blocks = x.reshape((2 * cp, s_blk) + x.shape[1:])
    out = blocks[jnp.asarray(order)].reshape((2 * cp * s_blk,) + x.shape[1:])
    return jnp.moveaxis(out, 0, axis)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "cp",
    causal: bool = True,
) -> jax.Array:
    """shard_map-interior ring attention.

    Call inside an existing ``shard_map`` whose in_specs shard the sequence
    axis (dim 1) of [b, s_local*2? ...] — here q/k/v are the *local* shard
    [b, s_local, h, d] where the global sequence was laid out with
    :func:`zigzag_split`. s_local must be even (two zig-zag blocks).
    """
    b, s_local, h, d = q.shape
    assert s_local % 2 == 0, "local seq must hold two zig-zag blocks"
    s_blk = s_local // 2
    reshape = lambda x: x.reshape(b, 2, s_blk, h, d)
    out = _ring_attention_local(
        reshape(q), reshape(k), reshape(v), axis_name=axis_name, causal=causal
    )
    return out.reshape(b, s_local, h, d)


def _ambient_mesh() -> Optional[Mesh]:
    """Back-compat alias — the lookup now lives in parallel/mesh.py where
    the flash kernel's TP wrapper shares it."""
    from fleetx_tpu.parallel.mesh import ambient_mesh

    return ambient_mesh()


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    cp_axis: str = "cp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "mp",
    causal: bool = True,
    expected_cp: Optional[int] = None,
) -> jax.Array:
    """Ring attention on globally-shaped [b, s, h, d] arrays.

    The sequence must already be in zig-zag order (:func:`zigzag_split`) —
    the data pipeline does this once (modules' ``cp_prepare``), so all
    layers see the permuted order (position ids carry the true positions;
    attention here is the only position-sensitive op).

    Wraps a shard_map over (batch_axes, cp_axis, head_axis); safe to call
    under jit inside the model — GSPMD sees a sharded custom region.

    ``expected_cp``: when the caller's config promises a cp degree, pass it —
    a missing/mismatched mesh axis then raises instead of silently running
    plain causal attention on zig-zag-ordered (i.e. wrongly ordered) data.
    """
    if mesh is None:
        mesh = _ambient_mesh()
    have_cp = mesh is not None and cp_axis in mesh.shape and mesh.shape[cp_axis] > 1
    if expected_cp and expected_cp > 1:
        if not have_cp or mesh.shape[cp_axis] != expected_cp:
            raise RuntimeError(
                f"model configured with cp_degree={expected_cp} but the "
                f"ambient mesh is {None if mesh is None else dict(mesh.shape)}; "
                "ring attention needs the 'cp' axis (inputs are zig-zag "
                "ordered — falling back would be silently wrong)"
            )
    if not have_cp:
        # No cp axis in play and none promised: inputs are in natural order,
        # plain attention is exact.
        from fleetx_tpu.ops.attention import causal_attention

        return causal_attention(q, k, v, causal=causal)

    spec = P(batch_axes, cp_axis, head_axis, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=cp_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
