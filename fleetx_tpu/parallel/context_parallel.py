"""Ring-attention context parallelism over a ``cp`` mesh axis.

The reference's only long-sequence mechanism is Megatron sequence parallel
tied to the TP degree (/root/reference/ppfleetx/models/language_model/gpt/
dygraph/sequence_parallel_utils.py:40-395) — activations are sharded
[s/n, b, h] *between* attention/FFN but every rank still materializes the
full sequence inside attention. This module goes beyond that with true
context parallelism: the sequence stays sharded *through* attention and
KV blocks rotate around the ``cp`` ring with ``lax.ppermute`` while each
device accumulates its queries' output with an online (flash-style)
softmax. Memory per device is O(s/cp) activations and O(s/cp) KV at a
time; the [s, s] score matrix never exists.

This is the TPU-native form of Ring Attention (blockwise parallel
transformers): the permute collective rides the ICI ring, and each hop
overlaps with the local attention block's compute under XLA async
collectives.

Causality is handled at block granularity with a zig-zag layout: device i
holds query/key blocks (i, 2*cp-1-i) of 2*cp equal slices, so every device
owns one "early" and one "late" block and the causal triangle's work is
balanced across the ring (a plain contiguous split leaves rank 0 almost
idle). `zigzag_split`/`zigzag_merge` convert between contiguous and
zig-zag order on the host or with pure reshapes under jit.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fleetx_tpu.ops.attention import NEG_INF


def _axis_size(axis_name: str) -> jax.Array:
    """Mapped-axis size across the jax API move: ``lax.axis_size`` where it
    exists, else the classic trace-time-constant ``psum(1, axis)`` idiom."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)

__all__ = [
    "ring_attention",
    "ring_self_attention",
    "zigzag_split",
    "zigzag_merge",
]


def _block_scores(q, k, scale):
    # q [b, sq, h, d] x k [b, sk, h, d] -> [b, h, sq, sk], fp32 accumulate.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    return s * scale


def _online_update(acc, m, l, scores, v):
    """One flash-attention accumulation step.

    acc [b, h, sq, d] fp32 running numerator, m [b, h, sq] running max,
    l [b, h, sq] running denominator; scores [b, h, sq, sk] fp32 (already
    masked); v [b, sk, h, d].
    """
    m_new = jnp.maximum(m, scores.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    acc_new = acc * corr[..., None] + pv
    return acc_new, m_new, l_new


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
) -> jax.Array:
    """Per-device body — runs inside shard_map; sequence axis sharded over
    ``axis_name``. q, k, v: [b, 2, s_blk, h, d] with the two zig-zag blocks
    stacked on dim 1 (block 0 = "early" slice, block 1 = "late" slice).
    """
    cp = _axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, two, s_blk, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))

    # Global block ids of this device's two zig-zag slices.
    my_blocks = jnp.stack([me, 2 * cp - 1 - me])  # [2]

    acc = jnp.zeros((2, b, h, s_blk, d), jnp.float32)
    m = jnp.full((2, b, h, s_blk), NEG_INF, jnp.float32)
    l = jnp.zeros((2, b, h, s_blk), jnp.float32)

    def accumulate(t, acc, m, l, k_cur, v_cur):
        # k_cur/v_cur originated on rank (me - t) mod cp.
        src = (me - t) % cp
        kv_blocks = jnp.stack([src, 2 * cp - 1 - src])  # [2]

        def one_pair(qi, acc_i, m_i, l_i):
            """Attend q block qi (global id my_blocks[qi]) over both kv blocks."""
            qb = q[:, qi]
            for kj in range(2):
                kb, vb = k_cur[:, kj], v_cur[:, kj]
                scores = _block_scores(qb, kb, scale)
                if causal:
                    q_pos = my_blocks[qi] * s_blk + jnp.arange(s_blk)[:, None]
                    k_pos = kv_blocks[kj] * s_blk + jnp.arange(s_blk)[None, :]
                    scores = scores + jnp.where(q_pos >= k_pos, 0.0, NEG_INF)
                acc_i, m_i, l_i = _online_update(acc_i, m_i, l_i, scores, vb)
            return acc_i, m_i, l_i

        new_acc, new_m, new_l = [], [], []
        for qi in range(2):
            a, mm, ll = one_pair(qi, acc[qi], m[qi], l[qi])
            new_acc.append(a)
            new_m.append(mm)
            new_l.append(ll)
        return jnp.stack(new_acc), jnp.stack(new_m), jnp.stack(new_l)

    def step(t, carry):
        acc, m, l, k_cur, v_cur = carry
        acc, m, l = accumulate(t, acc, m, l, k_cur, v_cur)
        # Rotate KV around the ring: rank r hands its buffer to r+1.
        perm = [(r, (r + 1) % cp) for r in range(cp)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    # cp-1 rotate-and-accumulate steps, then a peeled final accumulate so the
    # last (unused) KV rotation never hits the ICI.
    acc, m, l, k_last, v_last = lax.fori_loop(0, cp - 1, step, (acc, m, l, k, v))
    acc, m, l = accumulate(cp - 1, acc, m, l, k_last, v_last)
    # l is 0 only if every block was fully masked — impossible for causal
    # self-attention (the diagonal block always attends), so divide directly.
    out = acc / l[..., None]  # [2, b, h, s_blk, d]
    out = jnp.moveaxis(out, 2, 3)  # [2, b, s_blk, h, d]
    return out.transpose(1, 0, 2, 3, 4).astype(q.dtype)  # [b, 2, s_blk, h, d]


# ------------------------------------------------------ flash-kernel ring
# Default ring path (VERDICT r4 weak #4 closed): each hop's local block
# runs the Pallas flash kernel per (q-block, kv-block) pair instead of the
# jnp einsum online-softmax above — no [b, h, sq, sk] f32 score block ever
# reaches HBM, and the MXU sees bf16 tiles. Hops merge in (out, lse) space;
# the backward re-rotates K/V around the ring (rotating the dk/dv
# accumulators along) and feeds each pair the MERGED lse/delta, the
# flash-attention identity that makes per-hop gradients exact against the
# global softmax. Attention dropout composes: the kernel's bit stream is
# keyed on (seed, global batch*head, global positions) via its ``meta``
# input, and zig-zag block ids ARE original-order global positions, so the
# realized mask equals the single-device kernel's mask for any cp.

_NEG = -1e30


def _block_meta(shard_info, b_loc, h_loc, s_blk, q_blk_id, k_blk_id):
    """Kernel ``meta`` for one block pair: global batch/head offsets from
    the ambient manual axes + global position offsets from zig-zag ids."""
    batch_axes, (head_axis, mp) = shard_info
    b0 = jnp.int32(0)
    for name, size in batch_axes:
        b0 = b0 * size + lax.axis_index(name)
    h0 = (lax.axis_index(head_axis) if head_axis else jnp.int32(0))
    return jnp.stack([
        b0 * b_loc, h0 * h_loc, jnp.int32(h_loc), jnp.int32(h_loc * mp),
        (q_blk_id * s_blk).astype(jnp.int32),
        (k_blk_id * s_blk).astype(jnp.int32),
    ])


def _merge_lse(res, lse, o, l):
    """Fold one normalized hop result (o, l) into the running (res, lse):
    res' = res*exp(lse-L') + o*exp(l-L'), L' = logaddexp(lse, l)."""
    m = jnp.maximum(lse, l)
    l_new = m + jnp.log(jnp.exp(lse - m) + jnp.exp(l - m))
    res_new = (res * jnp.exp(lse - l_new)[..., None]
               + o.astype(jnp.float32) * jnp.exp(l - l_new)[..., None])
    return res_new, l_new


def _t0_pairs(causal: bool):
    """(q_slot, kv_slot, diag) pairs for the self-hop (t=0). Slot 0 = the
    'early' zig-zag block (global id me), slot 1 = 'late' (2cp-1-me)."""
    if causal:
        # (A,A) and (B,B) on the diagonal; (B,A) fully ordered since B > A
        return ((0, 0, True), (1, 1, True), (1, 0, False))
    return ((0, 0, False), (0, 1, False), (1, 0, False), (1, 1, False))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_flash(q, k, v, seed, axis_name, causal, dropout_rate, shard_info):
    out, _ = _ring_flash_fwd(q, k, v, seed, axis_name, causal, dropout_rate,
                             shard_info)
    return out


def _ring_flash_fwd(q, k, v, seed, axis_name, causal, dropout_rate,
                    shard_info):
    from fleetx_tpu.ops.pallas.flash_attention import block_fwd_lse

    cp = _axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, _, s_blk, h, d = q.shape
    s_tot = 2 * cp * s_blk
    q_ids = jnp.stack([me, 2 * cp - 1 - me])  # global zig-zag block ids

    def call(q_blk, q_id, k_blk, v_blk, k_id, diag):
        meta = _block_meta(shard_info, b, h, s_blk, q_id, k_id)
        return block_fwd_lse(q_blk, k_blk, v_blk, seed, meta, causal=diag,
                             dropout_rate=dropout_rate, kv_len=s_tot)

    res = [jnp.zeros((b, s_blk, h, d), jnp.float32) for _ in range(2)]
    lse = [jnp.full((b, s_blk, h), _NEG, jnp.float32) for _ in range(2)]
    for qi, ki, diag in _t0_pairs(causal):
        o, l = call(q[:, qi], q_ids[qi], k[:, ki], v[:, ki], q_ids[ki], diag)
        res[qi], lse[qi] = _merge_lse(res[qi], lse[qi], o, l)

    perm = [(r, (r + 1) % cp) for r in range(cp)]

    def hop(t, carry):
        resA, lseA, resB, lseB, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        src = (me - t) % cp
        kv_ids = jnp.stack([src, 2 * cp - 1 - src])
        if causal:
            # src < me (no ring wrap): kv block C is in both q blocks' past
            # -> (A,C), (B,C). src > me (wrapped): only the late q block B
            # is after both kv blocks -> (B,C), (B,D). Uniform shape: two
            # mask-free calls with where-selected operands.
            pred = src < me
            q1 = jnp.where(pred, q[:, 0], q[:, 1])
            q1_id = jnp.where(pred, q_ids[0], q_ids[1])
            o1, l1 = call(q1, q1_id, k_cur[:, 0], v_cur[:, 0], kv_ids[0],
                          False)
            mA = _merge_lse(resA, lseA, o1, l1)
            mB = _merge_lse(resB, lseB, o1, l1)
            resA = jnp.where(pred, mA[0], resA)
            lseA = jnp.where(pred, mA[1], lseA)
            resB = jnp.where(pred, resB, mB[0])
            lseB = jnp.where(pred, lseB, mB[1])
            k2 = jnp.where(pred, k_cur[:, 0], k_cur[:, 1])
            v2 = jnp.where(pred, v_cur[:, 0], v_cur[:, 1])
            k2_id = jnp.where(pred, kv_ids[0], kv_ids[1])
            o2, l2 = call(q[:, 1], q_ids[1], k2, v2, k2_id, False)
            resB, lseB = _merge_lse(resB, lseB, o2, l2)
        else:
            for qi in range(2):
                for ki in range(2):
                    o, l = call(q[:, qi], q_ids[qi], k_cur[:, ki],
                                v_cur[:, ki], kv_ids[ki], False)
                    if qi == 0:
                        resA, lseA = _merge_lse(resA, lseA, o, l)
                    else:
                        resB, lseB = _merge_lse(resB, lseB, o, l)
        return resA, lseA, resB, lseB, k_cur, v_cur

    resA, lseA, resB, lseB, _, _ = lax.fori_loop(
        1, cp, hop, (res[0], lse[0], res[1], lse[1], k, v)
    )
    out = jnp.stack([resA, resB], axis=1).astype(q.dtype)
    lse_all = jnp.stack([lseA, lseB], axis=1)  # [b, 2, s_blk, h] f32
    return out, (q, k, v, out, lse_all, seed)


def _ring_flash_bwd(axis_name, causal, dropout_rate, shard_info, res, g):
    from fleetx_tpu.ops.pallas.flash_attention import block_dkv, block_dq

    q, k, v, out, lse_all, seed = res
    cp = _axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, _, s_blk, h, d = q.shape
    s_tot = 2 * cp * s_blk
    q_ids = jnp.stack([me, 2 * cp - 1 - me])

    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [b, 2, s_blk, h]

    def dq_of(q_blk, q_id, k_blk, v_blk, k_id, do_blk, lse_blk, delta_blk,
              diag):
        meta = _block_meta(shard_info, b, h, s_blk, q_id, k_id)
        return block_dq(q_blk, k_blk, v_blk, do_blk, lse_blk, delta_blk,
                        seed, meta, causal=diag, dropout_rate=dropout_rate,
                        kv_len=s_tot)

    def dkv_of(q_blk, q_id, k_blk, v_blk, k_id, do_blk, lse_blk, delta_blk,
               diag):
        meta = _block_meta(shard_info, b, h, s_blk, q_id, k_id)
        return block_dkv(q_blk, k_blk, v_blk, do_blk, lse_blk, delta_blk,
                         seed, meta, causal=diag, dropout_rate=dropout_rate,
                         kv_len=s_tot)

    dq = [jnp.zeros((b, s_blk, h, d), jnp.float32) for _ in range(2)]
    dk_cur = jnp.zeros((b, 2, s_blk, h, d), jnp.float32)
    dv_cur = jnp.zeros((b, 2, s_blk, h, d), jnp.float32)
    for qi, ki, diag in _t0_pairs(causal):
        args = (q[:, qi], q_ids[qi], k[:, ki], v[:, ki], q_ids[ki],
                do[:, qi], lse_all[:, qi], delta[:, qi], diag)
        dq[qi] = dq[qi] + dq_of(*args)
        dk_c, dv_c = dkv_of(*args)
        dk_cur = dk_cur.at[:, ki].add(dk_c)
        dv_cur = dv_cur.at[:, ki].add(dv_c)

    perm = [(r, (r + 1) % cp) for r in range(cp)]

    def hop(t, carry):
        dqA, dqB, dk_cur, dv_cur, k_cur, v_cur = carry
        # K/V take the same tour as the forward; dk/dv accumulators ride
        # along so contributions stay co-located with their blocks
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        src = (me - t) % cp
        kv_ids = jnp.stack([src, 2 * cp - 1 - src])
        if causal:
            pred = src < me
            q1 = jnp.where(pred, q[:, 0], q[:, 1])
            q1_id = jnp.where(pred, q_ids[0], q_ids[1])
            do1 = jnp.where(pred, do[:, 0], do[:, 1])
            lse1 = jnp.where(pred, lse_all[:, 0], lse_all[:, 1])
            delta1 = jnp.where(pred, delta[:, 0], delta[:, 1])
            args1 = (q1, q1_id, k_cur[:, 0], v_cur[:, 0], kv_ids[0], do1,
                     lse1, delta1, False)
            dq1 = dq_of(*args1)
            dqA = dqA + jnp.where(pred, dq1, 0.0)
            dqB = dqB + jnp.where(pred, 0.0, dq1)
            dk1, dv1 = dkv_of(*args1)
            dk_cur = dk_cur.at[:, 0].add(dk1)
            dv_cur = dv_cur.at[:, 0].add(dv1)
            k2 = jnp.where(pred, k_cur[:, 0], k_cur[:, 1])
            v2 = jnp.where(pred, v_cur[:, 0], v_cur[:, 1])
            k2_id = jnp.where(pred, kv_ids[0], kv_ids[1])
            args2 = (q[:, 1], q_ids[1], k2, v2, k2_id, do[:, 1],
                     lse_all[:, 1], delta[:, 1], False)
            dqB = dqB + dq_of(*args2)
            dk2, dv2 = dkv_of(*args2)
            dk_cur = dk_cur.at[:, 0].add(jnp.where(pred, dk2, 0.0))
            dk_cur = dk_cur.at[:, 1].add(jnp.where(pred, 0.0, dk2))
            dv_cur = dv_cur.at[:, 0].add(jnp.where(pred, dv2, 0.0))
            dv_cur = dv_cur.at[:, 1].add(jnp.where(pred, 0.0, dv2))
        else:
            for qi in range(2):
                for ki in range(2):
                    args = (q[:, qi], q_ids[qi], k_cur[:, ki], v_cur[:, ki],
                            kv_ids[ki], do[:, qi], lse_all[:, qi],
                            delta[:, qi], False)
                    dq_c = dq_of(*args)
                    if qi == 0:
                        dqA = dqA + dq_c
                    else:
                        dqB = dqB + dq_c
                    dk_c, dv_c = dkv_of(*args)
                    dk_cur = dk_cur.at[:, ki].add(dk_c)
                    dv_cur = dv_cur.at[:, ki].add(dv_c)
        return dqA, dqB, dk_cur, dv_cur, k_cur, v_cur

    dqA, dqB, dk_cur, dv_cur, _, _ = lax.fori_loop(
        1, cp, hop, (dq[0], dq[1], dk_cur, dv_cur, k, v)
    )
    # contributions computed at hop t have travelled cp-1-t of the cp - t
    # forward rotations back to their origin rank: one more closes the ring
    dk = lax.ppermute(dk_cur, axis_name, perm)
    dv = lax.ppermute(dv_cur, axis_name, perm)
    dq_out = jnp.stack([dqA, dqB], axis=1)
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return (dq_out.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dseed)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def zigzag_split(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Contiguous -> zig-zag sequence order. Shape is unchanged; only the
    order along ``axis`` changes: the sequence is cut into 2*cp equal blocks
    and reordered to [b0, b_{2cp-1}, b1, b_{2cp-2}, ...], so an even split
    over cp devices gives device i its pair (b_i, b_{2cp-1-i}) contiguously
    — one "early" and one "late" block, balancing the causal triangle.
    """
    return _permute_blocks(x, cp, axis, invert=False)


def zigzag_merge(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Inverse of zigzag_split."""
    return _permute_blocks(x, cp, axis, invert=True)


def _zigzag_order(cp: int):
    order = []
    for i in range(cp):
        order += [i, 2 * cp - 1 - i]
    return order


def _permute_blocks(x: jax.Array, cp: int, axis: int, invert: bool) -> jax.Array:
    s = x.shape[axis]
    assert s % (2 * cp) == 0, f"seq {s} not divisible by 2*cp={2 * cp}"
    s_blk = s // (2 * cp)
    order = _zigzag_order(cp)
    if invert:
        inv = [0] * (2 * cp)
        for pos, blk in enumerate(order):
            inv[blk] = pos
        order = inv
    x = jnp.moveaxis(x, axis, 0)
    blocks = x.reshape((2 * cp, s_blk) + x.shape[1:])
    out = blocks[jnp.asarray(order)].reshape((2 * cp * s_blk,) + x.shape[1:])
    return jnp.moveaxis(out, 0, axis)


def _cp_flash_enabled() -> bool:
    """Flash-kernel ring is the default; FLEETX_CP_FLASH=0 restores the
    jnp online-softmax path (which supports no attention dropout)."""
    return os.environ.get("FLEETX_CP_FLASH", "1") == "1"


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "cp",
    causal: bool = True,
    dropout_rate: float = 0.0,
    seed: Optional[jax.Array] = None,
    shard_info=((), (None, 1)),
) -> jax.Array:
    """shard_map-interior ring attention.

    Call inside an existing ``shard_map`` whose in_specs shard the sequence
    axis (dim 1) of [b, s_local*2? ...] — here q/k/v are the *local* shard
    [b, s_local, h, d] where the global sequence was laid out with
    :func:`zigzag_split`. s_local must be even (two zig-zag blocks).

    ``dropout_rate > 0`` needs ``seed`` ([1] int32, replicated) and the
    flash path; ``shard_info`` = ((batch_axis, size), ...), (head_axis, mp))
    tells the kernel how to globalize batch/head ids for the dropout bit
    stream when batch/heads are themselves sharded in the same shard_map.
    """
    b, s_local, h, d = q.shape
    assert s_local % 2 == 0, "local seq must hold two zig-zag blocks"
    s_blk = s_local // 2
    reshape = lambda x: x.reshape(b, 2, s_blk, h, d)
    if dropout_rate > 0.0 and seed is None:
        raise ValueError(
            "ring_attention: dropout_rate > 0 requires an explicit seed "
            "([1] int32, replicated) — a silent default would reuse one "
            "mask across every call"
        )
    if _cp_flash_enabled() and s_blk % 8 == 0:
        if seed is None:
            seed = jnp.zeros((1,), jnp.int32)
        out = _ring_flash(
            reshape(q), reshape(k), reshape(v), seed, axis_name,
            bool(causal), float(dropout_rate), shard_info,
        )
        return out.reshape(b, s_local, h, d)
    if dropout_rate > 0.0:
        raise NotImplementedError(
            "attention dropout under context parallelism requires the "
            "flash ring path (seq/(2*cp) must be a multiple of 8 and "
            "FLEETX_CP_FLASH must not be 0)"
        )
    out = _ring_attention_local(
        reshape(q), reshape(k), reshape(v), axis_name=axis_name, causal=causal
    )
    return out.reshape(b, s_local, h, d)


def _ambient_mesh() -> Optional[Mesh]:
    """Back-compat alias — the lookup now lives in parallel/mesh.py where
    the flash kernel's TP wrapper shares it."""
    from fleetx_tpu.parallel.mesh import ambient_mesh

    return ambient_mesh()


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    cp_axis: str = "cp",
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "mp",
    causal: bool = True,
    expected_cp: Optional[int] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Ring attention on globally-shaped [b, s, h, d] arrays.

    The sequence must already be in zig-zag order (:func:`zigzag_split`) —
    the data pipeline does this once (modules' ``cp_prepare``), so all
    layers see the permuted order (position ids carry the true positions;
    attention here is the only position-sensitive op).

    Wraps a shard_map over (batch_axes, cp_axis, head_axis); safe to call
    under jit inside the model — GSPMD sees a sharded custom region.

    ``expected_cp``: when the caller's config promises a cp degree, pass it —
    a missing/mismatched mesh axis then raises instead of silently running
    plain causal attention on zig-zag-ordered (i.e. wrongly ordered) data.

    ``dropout_rate > 0`` (requires ``dropout_rng``) runs attention dropout
    inside the per-hop flash kernels; the realized mask is keyed on global
    (batch, head, position) ids, so it equals the non-cp kernel's mask.
    """
    if mesh is None:
        mesh = _ambient_mesh()
    have_cp = mesh is not None and cp_axis in mesh.shape and mesh.shape[cp_axis] > 1
    if expected_cp and expected_cp > 1:
        if not have_cp or mesh.shape[cp_axis] != expected_cp:
            raise RuntimeError(
                f"model configured with cp_degree={expected_cp} but the "
                f"ambient mesh is {None if mesh is None else dict(mesh.shape)}; "
                "ring attention needs the 'cp' axis (inputs are zig-zag "
                "ordered — falling back would be silently wrong)"
            )
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    if not have_cp:
        # No cp axis in play and none promised: inputs are in natural order,
        # plain attention is exact.
        from fleetx_tpu.ops.attention import causal_attention

        return causal_attention(
            q, k, v, causal=causal, dropout_rate=dropout_rate,
            dropout_rng=dropout_rng, deterministic=dropout_rate == 0.0,
        )

    if dropout_rate > 0.0:
        seed = jax.random.bits(dropout_rng, (1,), "uint32").astype(jnp.int32)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    # static picture of the manual axes for the kernel's global dropout ids
    shard_info = (
        tuple((a, mesh.shape[a]) for a in batch_axes if a in mesh.shape),
        (head_axis if head_axis in mesh.shape else None,
         mesh.shape.get(head_axis, 1)),
    )

    def body(q, k, v, seed):
        return ring_attention(
            q, k, v, axis_name=cp_axis, causal=causal,
            dropout_rate=dropout_rate, seed=seed, shard_info=shard_info,
        )

    spec = P(batch_axes, cp_axis, head_axis, None)
    from fleetx_tpu.parallel.mesh import shard_map

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(None)),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, seed)
