"""Dynamic axial parallelism (DAP) for Evoformer tensors.

The reference implements FastFold-style DAP with hand-written PyLayer
collectives over a dedicated process group
(/root/reference/ppfleetx/distributed/protein_folding/dap.py:28-401:
scatter/gather, all_gather, all_to_all, and the row↔col axis swaps
row_to_col/col_to_row; scg.py:28-224 builds the groups).

TPU-native: DAP is a *sharding layout*, not a set of collectives. MSA
activations [B, S, R, C] shard the sequence axis (row ops) or the residue
axis (col ops) over the ``cp`` mesh axis — the same axial-parallel mesh
axis ring attention uses, since a model is either a language model or a
folding trunk, never both in one step. ``row_to_col``/``col_to_row``
become a change of sharding constraint; GSPMD inserts exactly the
all_to_all the reference wrote by hand (dap.py:244-343), and overlaps it
with compute.

Branch parallelism (the reference's bp_degree=2 track split) is NOT layered
on top: DAP already distributes both evoformer tracks over the same
devices, so BP would only move FLOPs around while adding joins — see
fleetx_tpu/parallel/bp.py for the recorded decision and the shard_map
formulation provided for the cases that still want it.
"""

from __future__ import annotations

from flax import linen as nn
from jax.sharding import PartitionSpec as P

__all__ = ["row_sharded", "col_sharded", "pair_row_sharded", "pair_col_sharded"]

# Logical axis names resolved by make_rules' 'act_seq' machinery would tie
# us to the LM layout; the folding trunk declares its own:
#   dap_row  -> cp   (MSA sequence axis / pair first-residue axis)
#   dap_col  -> cp   (residue axis when column ops run)
# Only one of the two is applied to a given tensor at a time.
DAP_RULES = (("dap_axis", "cp"), ("dap_free", None), ("dap_batch", ("dp", "fsdp")))


def _constrain(x, spec):
    return nn.with_logical_constraint(x, P(*spec))


def row_sharded(msa):
    """[B, S, R, C] with the MSA-sequence axis S sharded: layout for row
    attention (each device holds whole rows -> reference dap.scatter(axis=1))."""
    return _constrain(msa, ("dap_batch", "dap_axis", "dap_free", None))


def col_sharded(msa):
    """[B, S, R, C] with the residue axis R sharded: layout for column
    attention. row_sharded -> col_sharded IS the reference's row_to_col
    all_to_all (dap.py:358-399), inserted by GSPMD."""
    return _constrain(msa, ("dap_batch", "dap_free", "dap_axis", None))


def pair_row_sharded(pair):
    """[B, R, R, C] pair tensor sharded over the first residue axis."""
    return _constrain(pair, ("dap_batch", "dap_axis", "dap_free", None))


def pair_col_sharded(pair):
    """[B, R, R, C] pair tensor sharded over the second residue axis."""
    return _constrain(pair, ("dap_batch", "dap_free", "dap_axis", None))


def dap_rules():
    """Logical-axis rules to install alongside the standard make_rules set."""
    return list(DAP_RULES)
