"""Mixture-of-Experts layer with expert parallelism — GShard-style dense
dispatch/combine einsums.

Capability parity with the reference MoE stack (/root/reference/ppfleetx/
distributed/moe/moe_layer.py:33-235 ``MoELayer`` + comm_ops.py ``MoEScatter``/
``MoEGather`` + gate/*.py ``NaiveGate``/``GShardGate``/``SwitchGate`` +
utils.py ``limit_by_capacity``), redesigned TPU-first: instead of explicit
count_by_gate + NCCL all-to-all scatter/gather, routing builds dispatch and
combine tensors and three einsums move tokens; with expert weights sharded
over the ('dp','fsdp') mesh axes GSPMD lowers the einsums to exactly the
all-to-all exchange the reference hand-writes. Capacity dropping, top-k
weighting, aux balance loss, and gate-noise semantics are preserved.

Gates:
- naive   — top-k softmax, no capacity drop (naive_gate.py:28)
- gshard  — top-2, capacity, aux balance loss, probabilistic 2nd-expert
            (random routing, gshard_gate.py:29-73)
- switch  — top-1, capacity, jitter noise, switch balance loss
            (switch_gate.py:29)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from fleetx_tpu.models.gpt import model as gpt_model

__all__ = ["MoEMLP", "compute_routing", "compute_routing_indices"]


def _balance_loss(gate_probs: jax.Array, expert_mask: jax.Array) -> jax.Array:
    """GShard/Switch auxiliary load-balance loss:
    E * sum_e mean(prob_e) * mean(assigned_e)."""
    num_experts = gate_probs.shape[-1]
    density = expert_mask.mean(axis=0)  # fraction of tokens per expert
    density_proxy = gate_probs.mean(axis=0)  # mean router prob per expert
    return num_experts * jnp.sum(density * density_proxy)


def compute_routing_indices(
    gate_logits: jax.Array,  # [n_tokens, E]
    top_k: int,
    capacity: int,
    gate_type: str = "gshard",
    rng: Optional[jax.Array] = None,
):
    """Sparse routing decisions: per (token, slot) the chosen expert, its
    queue position, the combine weight, and the keep flag, plus the aux
    balance loss. Tokens beyond an expert's capacity are dropped (reference
    limit_by_capacity, moe/utils.py:125). O(n*k) memory — the scalable form
    both dispatch implementations derive from."""
    n, num_experts = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    topk_probs, topk_idx = jax.lax.top_k(probs, top_k)

    if gate_type == "gshard" and top_k >= 2 and rng is not None:
        # random routing: 2nd expert kept with prob proportional to its gate
        # weight (reference gshard_gate.py:67-72)
        keep2 = jax.random.uniform(rng, (n,)) < (2.0 * topk_probs[:, 1])
        topk_probs = topk_probs.at[:, 1].set(
            jnp.where(keep2, topk_probs[:, 1], 0.0)
        )

    # normalize kept weights
    denom = jnp.maximum(topk_probs.sum(axis=-1, keepdims=True), 1e-9)
    topk_weights = topk_probs / denom

    # aux loss uses the top-1 assignment mask (Switch/GShard convention)
    top1_mask = jax.nn.one_hot(topk_idx[:, 0], num_experts)
    aux = _balance_loss(probs, top1_mask)

    # queue position of each (token, slot) in its expert, slots filled in
    # priority order (slot 0 of all tokens first — GShard convention)
    pos = jnp.zeros((n, top_k), jnp.int32)
    keep = jnp.zeros((n, top_k), jnp.bool_)
    fill = jnp.zeros((num_experts,), jnp.int32)
    for slot in range(top_k):
        e = topk_idx[:, slot]
        onehot = jax.nn.one_hot(e, num_experts, dtype=jnp.int32)
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) + fill[None, :]
        p = jnp.take_along_axis(pos_in_expert, e[:, None], axis=1)[:, 0]
        k = (p < capacity) & (topk_weights[:, slot] > 0)
        pos = pos.at[:, slot].set(jnp.clip(p, 0, capacity - 1))
        keep = keep.at[:, slot].set(k)
        fill = fill + onehot.sum(axis=0)

    return topk_idx, pos, topk_weights, keep, aux


def compute_routing(
    gate_logits: jax.Array,  # [n_tokens, E]
    top_k: int,
    capacity: int,
    gate_type: str = "gshard",
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Dense form: (dispatch [n, E, C] bool, combine [n, E, C] float,
    aux_loss), materialized from the sparse decisions. Memory scales as
    n*E*C — fine for small expert counts, use the index path at scale."""
    n, num_experts = gate_logits.shape
    topk_idx, pos, topk_weights, keep, aux = compute_routing_indices(
        gate_logits, top_k, capacity, gate_type, rng
    )
    dispatch = jnp.zeros((n, num_experts, capacity), jnp.bool_)
    combine = jnp.zeros((n, num_experts, capacity), jnp.float32)
    rows = jnp.arange(n)
    for slot in range(topk_idx.shape[1]):
        e = topk_idx[:, slot]
        p = pos[:, slot]
        k = keep[:, slot]
        dispatch = dispatch.at[rows, e, p].max(k)
        combine = combine.at[rows, e, p].add(
            jnp.where(k, topk_weights[:, slot], 0.0)
        )
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense MLP inside a decoder layer
    (reference ExpertLayer + MoELayer wiring, single_model.py:45-65,433-444).

    Expert FFN weights are stacked [E, ...] with the 'expert' logical axis
    sharded over the data axes; per-expert compute is batched einsum."""

    cfg: "gpt_model.GPTConfig"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, s, h = x.shape
        f = cfg.ffn_size
        E = cfg.num_experts
        n = b * s
        # switch gate is top-1 regardless of cfg.top_k; capacity must use the
        # effective k or switch capacity doubles vs the reference semantics
        eff_top_k = 1 if cfg.gate == "switch" else cfg.top_k
        capacity = max(1, int(cfg.capacity_factor * n * eff_top_k / E))

        tokens = x.reshape(n, h)

        gate_logits = nn.DenseGeneral(
            features=E,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                gpt_model.default_kernel_init, ("embed", None)
            ),
            name="gate",
        )(tokens.astype(jnp.float32))

        if cfg.gate == "switch" and self.has_rng("dropout"):
            # switch jitter noise
            noise = jax.random.uniform(
                self.make_rng("dropout"), gate_logits.shape, minval=0.98, maxval=1.02
            )
            gate_logits = gate_logits * noise

        rng = self.make_rng("dropout") if (cfg.gate == "gshard" and self.has_rng("dropout")) else None
        mode = getattr(cfg, "moe_dispatch", "auto")
        if mode not in ("auto", "einsum", "scatter"):
            raise ValueError(
                f"moe_dispatch={mode!r}; choose auto | einsum | scatter")
        if mode == "auto":
            # dense masks cost n*E*C floats; the scatter path costs n*h
            # gathers — switch over when the masks would exceed the
            # activations they route (capacity is ~n*k/E, so the dense form
            # grows quadratically in tokens)
            mode = "scatter" if n * E * capacity > 8 * n * h else "einsum"

        def ffn_param(name, shape, axes):
            return self.param(
                name,
                nn.with_logical_partitioning(gpt_model.default_kernel_init, axes),
                shape,
                jnp.float32,
            )

        w_up = ffn_param("w_up", (E, h, f), ("expert", "embed", "mlp"))
        b_up = ffn_param("b_up", (E, f), ("expert", "mlp"))
        w_down = ffn_param("w_down", (E, f, h), ("expert", "mlp", "embed"))
        b_down = ffn_param("b_down", (E, h), ("expert", "embed"))

        dt = cfg.dtype
        if mode == "scatter":
            # index dispatch (reference MoEScatter/MoEGather all-to-all
            # semantics, comm_ops.py:28-118): scatter-add tokens into the
            # per-expert buffers, gather weighted results back. GSPMD lowers
            # the token->expert reshuffle to the all-to-all the reference
            # hand-writes; no [n, E, C] mask is ever materialized.
            topk_idx, pos, weights, keep, aux = compute_routing_indices(
                gate_logits, eff_top_k, capacity, cfg.gate, rng
            )
            self.sow("intermediates", "balance_loss", aux)
            buf = jnp.zeros((E * capacity, h), dt)
            for slot in range(eff_top_k):
                flat = topk_idx[:, slot] * capacity + pos[:, slot]
                contrib = tokens.astype(dt) * keep[:, slot, None].astype(dt)
                buf = buf.at[flat].add(contrib)
            expert_in = buf.reshape(E, capacity, h)
            hidden = jax.nn.gelu(
                jnp.einsum("ech,ehf->ecf", expert_in, w_up.astype(dt))
                + b_up[:, None, :].astype(dt),
                approximate=True,
            )
            expert_out = (
                jnp.einsum("ecf,efh->ech", hidden, w_down.astype(dt))
                + b_down[:, None, :].astype(dt)
            ).reshape(E * capacity, h)
            out = jnp.zeros((n, h), dt)
            for slot in range(eff_top_k):
                flat = topk_idx[:, slot] * capacity + pos[:, slot]
                w = (weights[:, slot] * keep[:, slot]).astype(dt)[:, None]
                out = out + expert_out[flat] * w
            return out.reshape(b, s, h)

        dispatch, combine, aux = compute_routing(
            gate_logits, eff_top_k, capacity, cfg.gate, rng
        )
        self.sow("intermediates", "balance_loss", aux)
        expert_in = jnp.einsum(
            "nh,nec->ech", tokens.astype(dt), dispatch.astype(dt)
        )
        hidden = jax.nn.gelu(
            jnp.einsum("ech,ehf->ecf", expert_in, w_up.astype(dt))
            + b_up[:, None, :].astype(dt),
            approximate=True,
        )
        expert_out = (
            jnp.einsum("ecf,efh->ech", hidden, w_down.astype(dt))
            + b_down[:, None, :].astype(dt)
        )
        out = jnp.einsum(
            "ech,nec->nh", expert_out, combine.astype(dt)
        )
        return out.reshape(b, s, h)
