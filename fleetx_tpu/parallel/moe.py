"""Mixture-of-Experts layer with expert parallelism — GShard-style dense
dispatch/combine einsums.

Capability parity with the reference MoE stack (/root/reference/ppfleetx/
distributed/moe/moe_layer.py:33-235 ``MoELayer`` + comm_ops.py ``MoEScatter``/
``MoEGather`` + gate/*.py ``NaiveGate``/``GShardGate``/``SwitchGate`` +
utils.py ``limit_by_capacity``), redesigned TPU-first: instead of explicit
count_by_gate + NCCL all-to-all scatter/gather, routing builds dispatch and
combine tensors and three einsums move tokens; with expert weights sharded
over the ('dp','fsdp') mesh axes GSPMD lowers the einsums to exactly the
all-to-all exchange the reference hand-writes. Capacity dropping, top-k
weighting, aux balance loss, and gate-noise semantics are preserved.

Gates:
- naive   — top-k softmax, no capacity drop (naive_gate.py:28)
- gshard  — top-2, capacity, aux balance loss, probabilistic 2nd-expert
            (random routing, gshard_gate.py:29-73)
- switch  — top-1, capacity, jitter noise, switch balance loss
            (switch_gate.py:29)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from fleetx_tpu.models.gpt import model as gpt_model

__all__ = ["MoEMLP", "compute_routing"]


def _balance_loss(gate_probs: jax.Array, expert_mask: jax.Array) -> jax.Array:
    """GShard/Switch auxiliary load-balance loss:
    E * sum_e mean(prob_e) * mean(assigned_e)."""
    num_experts = gate_probs.shape[-1]
    density = expert_mask.mean(axis=0)  # fraction of tokens per expert
    density_proxy = gate_probs.mean(axis=0)  # mean router prob per expert
    return num_experts * jnp.sum(density * density_proxy)


def compute_routing(
    gate_logits: jax.Array,  # [n_tokens, E]
    top_k: int,
    capacity: int,
    gate_type: str = "gshard",
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (dispatch [n, E, C] bool, combine [n, E, C] float, aux_loss).

    Tokens beyond an expert's capacity are dropped (contribute zero output),
    matching the reference's limit_by_capacity (moe/utils.py:125).
    """
    n, num_experts = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    topk_probs, topk_idx = jax.lax.top_k(probs, top_k)

    if gate_type == "gshard" and top_k >= 2 and rng is not None:
        # random routing: 2nd expert kept with prob proportional to its gate
        # weight (reference gshard_gate.py:67-72)
        keep2 = jax.random.uniform(rng, (n,)) < (2.0 * topk_probs[:, 1])
        topk_probs = topk_probs.at[:, 1].set(
            jnp.where(keep2, topk_probs[:, 1], 0.0)
        )

    # normalize kept weights
    denom = jnp.maximum(topk_probs.sum(axis=-1, keepdims=True), 1e-9)
    topk_weights = topk_probs / denom

    # aux loss uses the top-1 assignment mask (Switch/GShard convention)
    top1_mask = jax.nn.one_hot(topk_idx[:, 0], num_experts)
    aux = _balance_loss(probs, top1_mask)

    # position of each token in its expert's queue, per top-k slot
    dispatch = jnp.zeros((n, num_experts, capacity), jnp.bool_)
    combine = jnp.zeros((n, num_experts, capacity), jnp.float32)
    fill = jnp.zeros((num_experts,), jnp.int32)
    for slot in range(top_k):
        e = topk_idx[:, slot]
        onehot = jax.nn.one_hot(e, num_experts, dtype=jnp.int32)
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) + fill[None, :]
        pos = jnp.take_along_axis(pos_in_expert, e[:, None], axis=1)[:, 0]
        keep = (pos < capacity) & (topk_weights[:, slot] > 0)
        pos_c = jnp.clip(pos, 0, capacity - 1)
        dispatch = dispatch.at[jnp.arange(n), e, pos_c].max(keep)
        combine = combine.at[jnp.arange(n), e, pos_c].add(
            jnp.where(keep, topk_weights[:, slot], 0.0)
        )
        fill = fill + onehot.sum(axis=0)

    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense MLP inside a decoder layer
    (reference ExpertLayer + MoELayer wiring, single_model.py:45-65,433-444).

    Expert FFN weights are stacked [E, ...] with the 'expert' logical axis
    sharded over the data axes; per-expert compute is batched einsum."""

    cfg: "gpt_model.GPTConfig"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, s, h = x.shape
        f = cfg.ffn_size
        E = cfg.num_experts
        n = b * s
        # switch gate is top-1 regardless of cfg.top_k; capacity must use the
        # effective k or switch capacity doubles vs the reference semantics
        eff_top_k = 1 if cfg.gate == "switch" else cfg.top_k
        capacity = max(1, int(cfg.capacity_factor * n * eff_top_k / E))

        tokens = x.reshape(n, h)

        gate_logits = nn.DenseGeneral(
            features=E,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                gpt_model.default_kernel_init, ("embed", None)
            ),
            name="gate",
        )(tokens.astype(jnp.float32))

        if cfg.gate == "switch" and self.has_rng("dropout"):
            # switch jitter noise
            noise = jax.random.uniform(
                self.make_rng("dropout"), gate_logits.shape, minval=0.98, maxval=1.02
            )
            gate_logits = gate_logits * noise

        rng = self.make_rng("dropout") if (cfg.gate == "gshard" and self.has_rng("dropout")) else None
        dispatch, combine, aux = compute_routing(
            gate_logits, eff_top_k, capacity, cfg.gate, rng
        )
        self.sow("intermediates", "balance_loss", aux)

        def ffn_param(name, shape, axes):
            return self.param(
                name,
                nn.with_logical_partitioning(gpt_model.default_kernel_init, axes),
                shape,
                jnp.float32,
            )

        w_up = ffn_param("w_up", (E, h, f), ("expert", "embed", "mlp"))
        b_up = ffn_param("b_up", (E, f), ("expert", "mlp"))
        w_down = ffn_param("w_down", (E, f, h), ("expert", "mlp", "embed"))
        b_down = ffn_param("b_down", (E, h), ("expert", "embed"))

        dt = cfg.dtype
        expert_in = jnp.einsum(
            "nh,nec->ech", tokens.astype(dt), dispatch.astype(dt)
        )
        hidden = jax.nn.gelu(
            jnp.einsum("ech,ehf->ecf", expert_in, w_up.astype(dt))
            + b_up[:, None, :].astype(dt),
            approximate=True,
        )
        expert_out = (
            jnp.einsum("ecf,efh->ech", hidden, w_down.astype(dt))
            + b_down[:, None, :].astype(dt)
        )
        out = jnp.einsum(
            "ech,nec->nh", expert_out, combine.astype(dt)
        )
        return out.reshape(b, s, h)
