"""Pipeline parallelism — GSPMD time-stepped pipeline.

Replaces the reference's PipelineLayer machinery (/root/reference/ppfleetx/
models/language_model/gpt/dygraph/hybrid_model.py:909-1096
``GPTForPretrainingPipe``: LayerDesc flattening, SharedLayerDesc embedding
tying, seg_method, fleet's 1F1B runtime with NCCL p2p send/recv) with an
SPMD formulation:

- decoder layers are stacked [pp, layers_per_stage, ...]; the leading axis
  carries the 'stage' logical name and is sharded over the ``pp`` mesh axis,
- the activation state buffer [pp, micro_bs, s, h] is likewise pp-sharded,
- one pipeline tick = roll(state, 1, axis=0) (XLA lowers to a collective
  permute between neighboring stages — the p2p send/recv) + a stage-vmapped
  layer application (each pp shard runs only its own stage's layers),
- the time loop is an nn.scan of num_microbatches + pp - 1 ticks with
  parameters broadcast across time.

Shared-embedding tying (reference SharedLayerDesc, hybrid_model.py:1012,1059)
falls out for free: embedding and logits head reference the same variable
outside the pipelined stack, and GSPMD sums its gradient contributions.

Gradient flow is standard autodiff through the scan; per-stage remat bounds
activation memory (the reference's 1F1B memory schedule is a runtime
scheduling choice NCCL needs; under XLA the scan + remat achieves the same
peak-memory class).

Virtual/interleaved stages (reference ``num_virtual_pipeline_stages``,
hybrid_model.py:1095): with ``virtual_pp=v`` each physical stage owns v
layer chunks and a microbatch traverses the stage ring v times. Two
schedules exist:

- **streamed** (default, ``FLEETX_VPP_STREAM=1``): ONE scan over a
  [v*pp, ...] state buffer — every chunk's stage applies in parallel each
  tick, chunk j+1 consumes chunk j's emission stream at pp-tick skew
  (row j*pp+pp-1 rolls straight into row (j+1)*pp), and the whole
  computation drains once: M + v*pp - 1 ticks total instead of the
  sequential schedule's v*(M + pp - 1). For M >> v*pp that is ~v x fewer
  scan ticks (collective permutes, loop iterations, per-tick dispatch),
  bought with dead-row work during the longer single fill/drain —
  tools/bench_pp_bubble.py --virtual-pp measures the trade and gates it.
  The param layout equals the plain pipe layout with v*pp stage rows
  (row g holds global chunk g = layers [g*lpc, (g+1)*lpc)), so the
  remap helpers and checkpoint converters need no new scopes.
- **sequential** (``FLEETX_VPP_STREAM=0``): chunk pass j is its own scan
  with statically selected chunk parameters, chained on pass j-1's
  emission stream — pass j fully drains (pp-1 dead ticks) before pass
  j+1 starts.

Both match the reference's math exactly (same layer order per
microbatch); the reference's interleaved 1F1B remains a *runtime*
schedule that a single statically-scheduled XLA program does not
express. Raising ``num_microbatches`` stays the primary bubble lever
(microbatches stream through one compiled scan, no host loop), and
virtual stages keep their other role: finer-grained layer placement so
each stage's weights/activations split v ways.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import jax.numpy as jnp
from flax import linen as nn

__all__ = [
    "PipelinedStack",
    "sequential_params_to_pipeline",
    "pipeline_params_to_sequential",
    "maybe_pipeline_params_to_sequential",
    "stream_chunks_default",
]


def stream_chunks_default() -> bool:
    """Whether virtual-pp chunks run the fused streamed schedule (module
    docstring). One resolution point so PipelinedStack, the param remap,
    and the init-via-sequential path can never disagree on layout."""
    return os.environ.get("FLEETX_VPP_STREAM", "1") == "1"

_SEQ_PREFIX = "gpt/layers/layer/"
_PIPE_PREFIX = "gpt/layers/pipe/stages/layers/layer/"
# single source of truth for the virtual-chunk scope name: the scan scope
# in PipelinedStack, the forward remap, the inverse regex, and the layout
# detector all derive from this template
_VPIPE_SCOPE = "pipe_chunk{j}"
_VPIPE_RE = "gpt/layers/" + _VPIPE_SCOPE + "/stages/layers/layer/"


def _flatten(variables):
    import flax

    params = variables["params"] if "params" in variables else variables
    flat = flax.traverse_util.flatten_dict(flax.core.unfreeze(params), sep="/")
    return flat, ("params" in variables)


def _unflatten(flat, wrap):
    import flax

    tree = flax.traverse_util.unflatten_dict(flat, sep="/")
    return {"params": tree} if wrap else tree


def sequential_params_to_pipeline(variables, pp: int, virtual_pp: int = 1,
                                  stream: Optional[bool] = None):
    """Remap a sequential-scan param tree (gpt/layers/layer/* with leading
    [num_layers] axis) to the pipeline layout: [pp, layers_per_stage]
    leading axes under gpt/layers/pipe/... — or, with virtual stages,
    either the STREAMED layout (one [v*pp, layers_per_chunk] tree under
    the same pipe scope, row g = global chunk g) or the sequential-chunk
    layout (one [pp, layers_per_chunk] tree per chunk pass, stage p of
    pass j holding global chunk j*pp + p — the reference's interleaved
    chunk placement). ``stream=None`` resolves from FLEETX_VPP_STREAM so
    the remap always matches what PipelinedStack will build."""
    if stream is None:
        stream = stream_chunks_default()
    if virtual_pp > 1 and stream:
        # streamed layout == the plain pipe layout with v*pp stage rows
        return sequential_params_to_pipeline(variables, pp * virtual_pp, 1)
    flat, wrap = _flatten(variables)
    out = {}
    for k, val in flat.items():
        if not k.startswith(_SEQ_PREFIX):
            out[k] = val
            continue
        suffix = k[len(_SEQ_PREFIX):]
        L = val.shape[0]
        if virtual_pp <= 1:
            out[_PIPE_PREFIX + suffix] = val.reshape(
                (pp, L // pp) + val.shape[1:])
            continue
        lpc = L // (pp * virtual_pp)
        # [L,...] -> [v*pp, lpc, ...]; pass j stage p = global chunk j*pp+p
        chunks = val.reshape((virtual_pp * pp, lpc) + val.shape[1:])
        for j in range(virtual_pp):
            out[_VPIPE_RE.format(j=j) + suffix] = chunks[
                j * pp:(j + 1) * pp]
    return _unflatten(out, wrap)


def pipeline_params_to_sequential(variables):
    """Inverse of :func:`sequential_params_to_pipeline` (plain and virtual
    layouts): merge the chunk/stage axes back into [num_layers] so a
    pipeline-trained checkpoint can drive the scan decode/eval path."""
    import re

    flat, wrap = _flatten(variables)
    out = {}
    vchunks = {}
    pattern = re.compile(
        "^" + re.escape(_VPIPE_RE.format(j="@")).replace("@", r"(\d+)") + "(.*)"
    )
    for k, v in flat.items():
        m = pattern.match(k)
        if m:
            j, suffix = int(m.group(1)), m.group(2)
            vchunks.setdefault(suffix, {})[j] = v
        elif k.startswith(_PIPE_PREFIX):
            nk = _SEQ_PREFIX + k[len(_PIPE_PREFIX):]
            out[nk] = v.reshape((v.shape[0] * v.shape[1],) + v.shape[2:])
        else:
            out[k] = v
    for suffix, by_chunk in vchunks.items():
        parts = [by_chunk[j] for j in sorted(by_chunk)]
        stacked = jnp.concatenate(parts, axis=0)  # [v*pp, lpc, ...]
        out[_SEQ_PREFIX + suffix] = stacked.reshape(
            (stacked.shape[0] * stacked.shape[1],) + stacked.shape[2:])
    return _unflatten(out, wrap)


def maybe_pipeline_params_to_sequential(variables):
    """Remap iff the tree holds pipeline-layout params; no-op otherwise."""
    flat, _ = _flatten(variables)
    marker = "/" + _VPIPE_SCOPE.format(j="")
    if any(k.startswith(_PIPE_PREFIX) or marker in k for k in flat):
        return pipeline_params_to_sequential(variables)
    return variables


class _StageStack(nn.Module):
    """layers_per_stage decoder layers applied in sequence (one stage)."""

    cfg: Any
    layer_cls: Callable
    layers_per_stage: int

    @nn.compact
    def __call__(self, x, attn_mask, deterministic):
        stack = nn.scan(
            self.layer_cls,
            variable_axes={"params": 0, "intermediates": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=self.layers_per_stage,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = stack(self.cfg, name="layers")(x, attn_mask, deterministic, False)
        return x


class _PipelineTick(nn.Module):
    """One pipeline time step: shift, inject, apply all stages in parallel.

    ``state``/``inject`` are (x, mask) pairs when a per-example attention
    mask streams with its microbatch (mask=None otherwise — batch-agnostic
    masks broadcast instead of streaming)."""

    cfg: Any
    layer_cls: Callable
    pp: int
    layers_per_stage: int

    @nn.compact
    def __call__(self, state, inject, attn_mask, deterministic):
        # shift: stage k receives stage k-1's output (ppermute over 'pp');
        # stage 0 receives the next microbatch
        x_state, m_state = state
        x_inj, m_inj = inject
        shifted = jnp.roll(x_state, 1, axis=0).at[0].set(x_inj)
        if m_state is not None:
            m_shifted = jnp.roll(m_state, 1, axis=0).at[0].set(m_inj)
            stage_mask_axis = 0
        else:
            m_shifted = attn_mask  # batch-agnostic: same for every stage
            stage_mask_axis = None
        stages = nn.vmap(
            _StageStack,
            in_axes=(0, stage_mask_axis, None),
            out_axes=0,
            variable_axes={"params": 0, "intermediates": 0},
            split_rngs={"params": True, "dropout": True},
            metadata_params={nn.PARTITION_NAME: "stage"},
        )
        shifted = nn.with_logical_constraint(
            shifted, ("stage", "act_batch", "act_seq", "act_embed")
        )
        new_state = stages(
            self.cfg, self.layer_cls, self.layers_per_stage, name="stages"
        )(shifted, m_shifted, deterministic)
        new_state = nn.with_logical_constraint(
            new_state, ("stage", "act_batch", "act_seq", "act_embed")
        )
        return (new_state, m_shifted if m_state is not None else None), \
            new_state[self.pp - 1]


class PipelinedStack(nn.Module):
    """Drop-in decoder stack for pp>1. Input [b, s, h]; b is split into
    ``num_microbatches`` microbatches that stream through the stages
    ``virtual_pp`` times (once per layer chunk). ``stream`` selects the
    fused one-scan virtual-chunk schedule (module docstring); None
    resolves from FLEETX_VPP_STREAM."""

    cfg: Any
    layer_cls: Callable
    pp: int
    num_microbatches: int
    virtual_pp: int = 1
    stream: Optional[bool] = None

    @nn.compact
    def __call__(self, x, attn_mask=None, deterministic=True):
        cfg = self.cfg
        pp = self.pp
        M = self.num_microbatches
        b, s, h = x.shape
        # per-example masks ([b, ...]) stream through the stage buffer with
        # their microbatch; batch-agnostic masks (leading dim 1 or None)
        # broadcast to every stage
        per_example = (
            attn_mask is not None and attn_mask.ndim >= 1
            and attn_mask.shape[0] == b and b > 1
        )
        if (attn_mask is not None and not per_example
                and attn_mask.shape[0] != 1):
            raise ValueError(
                "attn_mask leading dim must be the batch or 1, got "
                f"{attn_mask.shape} for batch {b}"
            )
        v = max(self.virtual_pp, 1)
        if cfg.num_layers % (pp * v):
            raise ValueError(
                f"num_layers {cfg.num_layers} % (pp {pp} * virtual {v}) != 0")
        if b % M:
            raise ValueError(f"batch {b} % num_microbatches {M} != 0")
        layers_per_stage = cfg.num_layers // (pp * v)
        mb = b // M
        streamed = self.stream if self.stream is not None \
            else stream_chunks_default()
        # streamed schedule: one logical pipe of v*pp chunk rows, drained
        # once; sequential schedule: v chained passes of pp rows each
        rows = pp * v if (v > 1 and streamed) else pp

        micro = x.reshape(M, mb, s, h)
        # pad the injection stream with rows-1 dead ticks to drain the pipe
        pad = jnp.zeros((rows - 1, mb, s, h), x.dtype)
        inject_stream = jnp.concatenate([micro, pad], axis=0)

        state0 = jnp.zeros((rows, mb, s, h), x.dtype)
        if per_example:
            m = attn_mask.reshape((M, mb) + attn_mask.shape[1:])
            m_pad = jnp.zeros((rows - 1,) + m.shape[1:], m.dtype)
            m_stream = jnp.concatenate([m, m_pad], axis=0)
            m_state0 = jnp.zeros((rows,) + m.shape[1:], m.dtype)
            bcast_mask = None
        else:
            m_stream = None
            m_state0 = None
            bcast_mask = attn_mask

        def chunk_pass(name, inj_stream):
            tick = nn.scan(
                _PipelineTick,
                variable_broadcast="params",
                variable_axes={"intermediates": 0},
                split_rngs={"params": False, "dropout": True},
                in_axes=((0, 0 if per_example else nn.broadcast), nn.broadcast,
                         nn.broadcast),
                out_axes=0,
                length=M + rows - 1,
            )
            _, emitted = tick(
                cfg, self.layer_cls, rows, layers_per_stage, name=name
            )((state0, m_state0), (inj_stream, m_stream), bcast_mask,
              deterministic)
            # microbatch m exits the last row at tick m + rows - 1
            return emitted[rows - 1:]

        if rows != pp or v == 1:
            # plain pipe (v == 1) and the streamed fusion share one scan
            # AND one param scope: the streamed layout IS the plain layout
            # with v*pp stage rows (row g = global chunk g), so checkpoint
            # remaps need no extra scopes
            out = chunk_pass("pipe", inject_stream)
            return out.reshape(b, s, h)

        stream = inject_stream
        for j in range(v):
            out = chunk_pass(_VPIPE_SCOPE.format(j=j), stream)
            if j < v - 1:
                stream = jnp.concatenate([out, pad], axis=0)
        return out.reshape(b, s, h)
