"""Pipeline parallelism — GSPMD time-stepped pipeline.

Replaces the reference's PipelineLayer machinery (/root/reference/ppfleetx/
models/language_model/gpt/dygraph/hybrid_model.py:909-1096
``GPTForPretrainingPipe``: LayerDesc flattening, SharedLayerDesc embedding
tying, seg_method, fleet's 1F1B runtime with NCCL p2p send/recv) with an
SPMD formulation:

- decoder layers are stacked [pp, layers_per_stage, ...]; the leading axis
  carries the 'stage' logical name and is sharded over the ``pp`` mesh axis,
- the activation state buffer [pp, micro_bs, s, h] is likewise pp-sharded,
- one pipeline tick = roll(state, 1, axis=0) (XLA lowers to a collective
  permute between neighboring stages — the p2p send/recv) + a stage-vmapped
  layer application (each pp shard runs only its own stage's layers),
- the time loop is an nn.scan of num_microbatches + pp - 1 ticks with
  parameters broadcast across time.

Shared-embedding tying (reference SharedLayerDesc, hybrid_model.py:1012,1059)
falls out for free: embedding and logits head reference the same variable
outside the pipelined stack, and GSPMD sums its gradient contributions.

Gradient flow is standard autodiff through the scan; per-stage remat bounds
activation memory (the reference's 1F1B memory schedule is a runtime
scheduling choice NCCL needs; under XLA the scan + remat achieves the same
peak-memory class).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = [
    "PipelinedStack",
    "sequential_params_to_pipeline",
    "pipeline_params_to_sequential",
    "maybe_pipeline_params_to_sequential",
]

_SEQ_PREFIX = "gpt/layers/layer/"
_PIPE_PREFIX = "gpt/layers/pipe/stages/layers/layer/"


def _flatten(variables):
    import flax

    params = variables["params"] if "params" in variables else variables
    flat = flax.traverse_util.flatten_dict(flax.core.unfreeze(params), sep="/")
    return flat, ("params" in variables)


def _unflatten(flat, wrap):
    import flax

    tree = flax.traverse_util.unflatten_dict(flat, sep="/")
    return {"params": tree} if wrap else tree


def sequential_params_to_pipeline(variables, pp: int):
    """Remap a sequential-scan param tree (gpt/layers/layer/* with leading
    [num_layers] axis) to the pipeline layout (gpt/layers/pipe/stages/
    layers/layer/* with leading [pp, layers_per_stage] axes)."""
    flat, wrap = _flatten(variables)
    out = {}
    for k, v in flat.items():
        if k.startswith(_SEQ_PREFIX):
            nk = _PIPE_PREFIX + k[len(_SEQ_PREFIX):]
            out[nk] = v.reshape((pp, v.shape[0] // pp) + v.shape[1:])
        else:
            out[k] = v
    return _unflatten(out, wrap)


def pipeline_params_to_sequential(variables):
    """Inverse of :func:`sequential_params_to_pipeline`: merge the
    [pp, layers_per_stage] leading axes back into [num_layers] so a
    pipeline-trained checkpoint can drive the scan decode/eval path."""
    flat, wrap = _flatten(variables)
    out = {}
    for k, v in flat.items():
        if k.startswith(_PIPE_PREFIX):
            nk = _SEQ_PREFIX + k[len(_PIPE_PREFIX):]
            out[nk] = v.reshape((v.shape[0] * v.shape[1],) + v.shape[2:])
        else:
            out[k] = v
    return _unflatten(out, wrap)


def maybe_pipeline_params_to_sequential(variables):
    """Remap iff the tree holds pipeline-layout params; no-op otherwise."""
    flat, _ = _flatten(variables)
    if any(k.startswith(_PIPE_PREFIX) for k in flat):
        return pipeline_params_to_sequential(variables)
    return variables


class _StageStack(nn.Module):
    """layers_per_stage decoder layers applied in sequence (one stage)."""

    cfg: Any
    layer_cls: Callable
    layers_per_stage: int

    @nn.compact
    def __call__(self, x, attn_mask, deterministic):
        stack = nn.scan(
            self.layer_cls,
            variable_axes={"params": 0, "intermediates": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=self.layers_per_stage,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = stack(self.cfg, name="layers")(x, attn_mask, deterministic, False)
        return x


class _PipelineTick(nn.Module):
    """One pipeline time step: shift, inject, apply all stages in parallel."""

    cfg: Any
    layer_cls: Callable
    pp: int
    layers_per_stage: int

    @nn.compact
    def __call__(self, state, inject, attn_mask, deterministic):
        # shift: stage k receives stage k-1's output (ppermute over 'pp');
        # stage 0 receives the next microbatch
        shifted = jnp.roll(state, 1, axis=0)
        shifted = shifted.at[0].set(inject)
        stages = nn.vmap(
            _StageStack,
            in_axes=(0, None, None),
            out_axes=0,
            variable_axes={"params": 0, "intermediates": 0},
            split_rngs={"params": True, "dropout": True},
            metadata_params={nn.PARTITION_NAME: "stage"},
        )
        shifted = nn.with_logical_constraint(
            shifted, ("stage", "act_batch", "act_seq", "act_embed")
        )
        new_state = stages(
            self.cfg, self.layer_cls, self.layers_per_stage, name="stages"
        )(shifted, attn_mask, deterministic)
        new_state = nn.with_logical_constraint(
            new_state, ("stage", "act_batch", "act_seq", "act_embed")
        )
        return new_state, new_state[self.pp - 1]


class PipelinedStack(nn.Module):
    """Drop-in decoder stack for pp>1. Input [b, s, h]; b is split into
    ``num_microbatches`` microbatches that stream through the stages."""

    cfg: Any
    layer_cls: Callable
    pp: int
    num_microbatches: int

    @nn.compact
    def __call__(self, x, attn_mask=None, deterministic=True):
        cfg = self.cfg
        pp = self.pp
        M = self.num_microbatches
        b, s, h = x.shape
        if attn_mask is not None and attn_mask.ndim >= 1 and attn_mask.shape[0] not in (1,):
            # a per-example mask would need to stream through the stage
            # buffer alongside x; only batch-agnostic masks are supported
            raise ValueError(
                "PipelinedStack supports only batch-agnostic attn_mask "
                f"(leading dim 1), got shape {attn_mask.shape}"
            )
        if cfg.num_layers % pp:
            raise ValueError(f"num_layers {cfg.num_layers} % pp {pp} != 0")
        if b % M:
            raise ValueError(f"batch {b} % num_microbatches {M} != 0")
        layers_per_stage = cfg.num_layers // pp
        mb = b // M

        micro = x.reshape(M, mb, s, h)
        # pad the injection stream with pp-1 dead ticks to drain the pipe
        pad = jnp.zeros((pp - 1, mb, s, h), x.dtype)
        inject_stream = jnp.concatenate([micro, pad], axis=0)

        state0 = jnp.zeros((pp, mb, s, h), x.dtype)

        tick = nn.scan(
            _PipelineTick,
            variable_broadcast="params",
            variable_axes={"intermediates": 0},
            split_rngs={"params": False, "dropout": True},
            in_axes=(0, nn.broadcast, nn.broadcast),
            out_axes=0,
            length=M + pp - 1,
        )
        _, emitted = tick(
            cfg, self.layer_cls, pp, layers_per_stage, name="pipe"
        )(state0, inject_stream, attn_mask, deterministic)

        # microbatch m exits the last stage at tick m + pp - 1
        out = emitted[pp - 1 :]
        return out.reshape(b, s, h)
