"""Parallelism layer: mesh, sharding rules, pipeline/MoE/context-parallel, DAP."""
