"""Branch parallelism (BP) for the folding trunk — TPU formulation.

The reference runs each Evoformer block's two tracks on different ranks of
a 2-way process group (/root/reference/ppfleetx/distributed/protein_folding/
bp.py:52 ``broadcast_grad_for_backward``, group setup scg.py:28-224): the
MSA track (row/column attention + transition) on bp rank 0 and the pair
track (triangle multiplications/attentions) on bp rank 1, concurrently,
re-joining at the block boundary with broadcasts and an all-reduce of the
shared pair gradient (evoformer.py:277-341). This requires the
outer-product-mean to move to the end of the block
(``outer_product_mean_position == 'end'``, evoformer.py:54) so the two
tracks are data-independent within a block.

Why this is NOT the default on TPU (recorded design decision, VERDICT r3
missing #1): under DAP both tracks already shard over the ``cp`` mesh axis
— every device computes 1/cp of the MSA track *and* 1/cp of the pair track
(tests/test_folding_trunk.py asserts the per-device shard shapes and the
all-to-all layout swaps). Dedicating half the devices to each track moves
the same FLOPs around (each device computes 2/bp of one track instead of
1/cp of both) while adding two broadcast joins and a pair-grad all-reduce
per block, and inherits the tracks' load imbalance. BP pays off only when
per-rank kernels are too small to saturate a GPU — the MXU's preference
for larger per-device tiles argues the opposite way on TPU.

For the cases where branch-level decomposition is still wanted (e.g. track
kernels that cannot shard further), :func:`branch_parallel2` expresses the
reference's semantics TPU-natively: one ``shard_map`` over a 2-way axis,
``lax.cond`` on ``axis_index`` so each device executes only its branch
(TPU programs own their control flow, so the untaken branch is skipped at
run time, not masked), and a ``psum`` join — whose transpose is exactly the
reference's hand-written gradient all-reduce. Replicated closure params get
summed cotangents from shard_map's transpose for free (bp.py:64-77
``BroadcastGrad`` equivalent).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["branch_parallel2"]


def branch_parallel2(
    fn0: Callable[..., Any],
    fn1: Callable[..., Any],
    args0: Tuple,
    args1: Tuple,
    mesh,
    axis: str = "cp",
):
    """Evaluate ``fn0(*args0)`` on even ranks and ``fn1(*args1)`` on odd
    ranks of ``mesh.shape[axis]`` (which must be even), returning both
    results replicated — the reference's bp_degree=2 branch split.

    Inputs are taken replicated over ``axis`` (the trunk's activations are
    replicated over cp between DAP regions); each device runs only its
    branch, and the join ``psum`` broadcasts results everywhere. Gradients:
    the untaken branch contributes exact zeros, so the psum transpose
    reproduces the reference's pair-grad all-reduce (evoformer.py:279).

    fn0/fn1 must be jax-traceable with array (pytree) args and outputs.
    """
    if mesh.shape[axis] % 2:
        raise ValueError(
            f"branch_parallel2 needs an even '{axis}' axis, got {mesh.shape[axis]}"
        )
    out0_sd = jax.eval_shape(fn0, *args0)
    out1_sd = jax.eval_shape(fn1, *args1)

    def _zeros(sd_tree):
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), sd_tree)

    def body(args0, args1):
        idx = jax.lax.axis_index(axis)
        y0 = jax.lax.cond(
            idx % 2 == 0, lambda a: fn0(*a), lambda a: _zeros(out0_sd), args0
        )
        y1 = jax.lax.cond(
            idx % 2 == 1, lambda a: fn1(*a), lambda a: _zeros(out1_sd), args1
        )
        # each branch ran on half the ranks: average over the axis so the
        # replicated join is exact regardless of the axis size
        n_half = mesh.shape[axis] // 2
        y0 = jax.tree.map(lambda t: jax.lax.psum(t, axis) / n_half, y0)
        y1 = jax.tree.map(lambda t: jax.lax.psum(t, axis) / n_half, y1)
        return y0, y1

    replicated = jax.tree.map(lambda _: P(), (args0, args1))
    out_spec = jax.tree.map(lambda _: P(), (out0_sd, out1_sd))
    from fleetx_tpu.parallel.mesh import shard_map

    return shard_map(
        body, mesh=mesh, in_specs=replicated, out_specs=out_spec,
        check_vma=False,
    )(args0, args1)
