"""Logical-axis sharding rules — the TPU-native replacement for the
reference's per-strategy wrappers:

- TP column/row/vocab-parallel layers (hybrid_model.py:49-174,628-680) become
  rules mapping the ``heads``/``mlp``/``vocab`` logical axes to mesh axis
  ``mp``; GSPMD inserts the all-reduce/all-gather that Column/RowParallelLinear
  did by hand.
- ZeRO sharding stages 1-3 (distributed/apis/sharding.py:30-147) become the
  ``fsdp`` mesh axis applied to optimizer state (stage 1/2) and additionally
  to parameters (stage 3).
- Megatron sequence parallel (sequence_parallel_utils.py:40-395) becomes an
  activation sharding constraint putting the ``seq`` logical axis on ``mp``;
  XLA's collective-matmul pass emits the same all-gather/reduce-scatter
  overlap the hand-written ScatterOp/GatherOp/ReduceScatterOp provided.

Models annotate params/activations with logical axis names (flax
``nn.with_partitioning`` / ``logical_to_mesh``); these tables translate
logical names → mesh axes for a given parallelism configuration.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

__all__ = [
    "make_rules",
    "logical_to_mesh_sharding",
    "param_shardings",
    "serving_param_shardings",
    "with_logical_constraint",
    "zero_update_spec",
]

Rules = Sequence[Tuple[str, Any]]


def make_rules(
    sharding_stage: int = 1,
    sequence_parallel: bool = False,
    fsdp_params: Optional[bool] = None,
    context_parallel: bool = False,
) -> List[Tuple[str, Any]]:
    """Logical→mesh axis rules.

    ``fsdp_params`` overrides whether *parameters* (not just optimizer state)
    are sharded over the fsdp axis; default derives from sharding_stage>=3.
    ``context_parallel`` puts the activation sequence axis on ``cp`` so the
    whole layer stack (embeddings, MLP, logits) — not just attention — holds
    O(s/cp) per device; zig-zag order is position-agnostic for everything
    outside attention, which re-orders via its own shard_map.
    """
    if fsdp_params is None:
        fsdp_params = sharding_stage >= 3
    rules: List[Tuple[str, Any]] = [
        ("batch", ("dp", "fsdp")),
        # TP: vocab-, column- (heads/mlp out), and row-parallel (reduced-in)
        ("vocab", "mp"),
        ("heads", "mp"),
        ("kv", None),
        ("mlp", "mp"),
        # embed is the row-parallel contraction axis of out-proj / mlp.down and
        # the fsdp shard axis for stage-3 param sharding.
        ("embed", "fsdp" if fsdp_params else None),
        ("norm", None),
        ("layers", None),  # stacked (scan) layer axis; pp maps it to stages
        ("stage", "pp"),
        # expert parallelism folds over the data-parallel world (reference
        # HybridCommGroupForMoE fuses moe = dp×mp, comm_groups.py:125-153;
        # here experts shard over dp×fsdp and mp shards within an expert).
        ("expert", ("dp", "fsdp")),
        ("cache_batch", None),
        ("cache_heads", "mp"),
    ]
    # Activation sequence axis: sharded over cp under context parallelism
    # (optionally also mp for Megatron-SP), over mp alone for pure SP, over
    # nothing otherwise. 'act_seq' only tags activations, never params.
    if context_parallel:
        rules.append(("act_seq", ("cp", "mp") if sequence_parallel else "cp"))
    elif sequence_parallel:
        rules.append(("act_seq", "mp"))
    else:
        rules.append(("act_seq", None))
    rules.append(("act_batch", ("dp", "fsdp")))
    rules.append(("act_embed", None))
    return rules


def logical_to_mesh_sharding(tree, mesh: Mesh, rules: Rules):
    """Map a pytree of logical PartitionSpecs to NamedShardings on mesh."""
    return nn.logical_to_mesh_sharding(tree, mesh, list(rules))


def param_shardings(abstract_vars, mesh: Mesh, rules: Rules):
    """NamedShardings for a flax variables pytree whose params carry
    ``nn.Partitioned`` logical-axis metadata (from nn.with_partitioning)."""
    logical_specs = nn.get_partition_spec(abstract_vars)
    return logical_to_mesh_sharding(logical_specs, mesh, rules)


def with_logical_constraint(x, logical_axes: Tuple[Optional[str], ...]):
    """Annotate an activation with logical axes (no-op outside a mesh ctx)."""
    return nn.with_logical_constraint(x, P(*logical_axes))


def _spec_axes(entry) -> Tuple[str, ...]:
    """Mesh axes named by one PartitionSpec entry (str | tuple | None)."""
    if entry is None:
        return ()
    if isinstance(entry, tuple):
        return tuple(a for a in entry if a)
    return (entry,)


def _fit_spec(spec: P, shape, mesh: Mesh) -> P:
    """Clamp a PartitionSpec to the dims it evenly divides: entries whose
    mesh-axis product does not divide the dimension are dropped
    (replicated) instead of erroring — a prime vocab under mp2, or the
    size-1 dims of a per-channel quantization scale, simply stay whole."""
    parts = []
    for i, dim in enumerate(shape):
        entry = spec[i] if i < len(spec) else None
        factor = math.prod(int(mesh.shape[a]) for a in _spec_axes(entry))
        parts.append(entry if dim % factor == 0 else None)
    return P(*parts)


def serving_param_shardings(abstract_params, params, mesh: Mesh,
                            rules: Rules):
    """Per-leaf NamedShardings for a SERVED (inference) param tree.

    ``abstract_params`` is the module's ``eval_shape`` init — its
    ``nn.Partitioned`` metadata is the source of each param's logical
    spec; ``params`` is the tree actually served, which may be unboxed
    and may carry int8-quantized ``{"_q8", "_scale"}`` sub-dicts in
    place of float kernels (``ops/quant.quantize_tree_int8``). A
    ``_q8`` leaf inherits its kernel's spec; a ``_scale`` leaf inherits
    it too but its keepdims-1 dims (and any other non-dividing dim)
    drop their axes via :func:`_fit_spec`, so scales end up replicated
    unless their channel axis is genuinely sharded. Leaves with no
    metadata (or paths the abstract tree lacks) replicate."""
    from jax.sharding import NamedSharding
    from jax.tree_util import tree_flatten_with_path, tree_map_with_path

    logical = nn.get_partition_spec(abstract_params)
    mesh_sh = logical_to_mesh_sharding(logical, mesh, list(rules))

    def path_names(path):
        return tuple(str(getattr(k, "key", k)) for k in path)

    by_path = {path_names(p): sh
               for p, sh in tree_flatten_with_path(mesh_sh)[0]}

    def one(path, leaf):
        names = path_names(path)
        if names and names[-1] in ("_q8", "_scale"):
            names = names[:-1]
        sh = by_path.get(names)
        spec = sh.spec if sh is not None else P()
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, _fit_spec(spec, shape, mesh))

    return tree_map_with_path(one, params)


def zero_update_spec(spec: Optional[P], shape, mesh: Mesh,
                     axes: Sequence[str] = ("dp", "fsdp")) -> P:
    """PartitionSpec of one parameter's ZeRO *weight-update shard*
    (arxiv 2004.13336: shard the optimizer update across the data-parallel
    replicas, all-gather the result).

    Folds the not-yet-used data-parallel mesh axes onto the first dimension
    they divide evenly — on top of any existing tensor-parallel sharding, so
    a dp x mp config shards the update dp ways *within* each mp shard. Tries
    the full dp x fsdp product first (maximum shard factor), then each axis
    alone. Leaves that no axis divides (tiny biases, scalars) keep their
    original spec and stay replicated — correct, just not sharded."""
    spec = spec if spec is not None else P()
    if not getattr(shape, "__len__", None) or len(shape) == 0:
        return spec
    used = {a for entry in spec for a in _spec_axes(entry)}
    free = [a for a in axes
            if a in mesh.shape and mesh.shape[a] > 1 and a not in used]
    if not free:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    candidates = [tuple(free)]
    if len(free) > 1:
        candidates += [(a,) for a in free]
    for cand in candidates:
        factor = math.prod(int(mesh.shape[a]) for a in cand)
        for i, dim in enumerate(shape):
            cur = _spec_axes(parts[i])
            cur_factor = math.prod(int(mesh.shape[a]) for a in cur)
            if dim % (cur_factor * factor):
                continue
            merged = cur + cand
            parts[i] = merged if len(merged) > 1 else merged[0]
            return P(*parts)
    return spec
