"""Device-mesh construction — the TPU-native replacement for the reference's
three group managers (fleet HybridCommunicateGroup, OrthogonalStrategy,
SingletonCommunicationGroup; /root/reference/ppfleetx/distributed/apis/
env.py:85-114, comm_groups.py:27-153, protein_folding/scg.py:28-224).

One `jax.sharding.Mesh` with named axes replaces them all: collectives are
inserted by GSPMD from sharding annotations, or written explicitly with
``shard_map`` over the same axes. Axis names:

- ``dp``     data parallel (pure replication of params)
- ``fsdp``   data parallel with ZeRO param/opt-state sharding (sharding_degree)
- ``pp``     pipeline stages
- ``cp``     context parallel (ring attention; sequence sharded through attn)
- ``mp``     tensor ("model") parallel; sequence parallel rides this axis
- ``ep``     expert parallel for MoE (folded over dp×fsdp when used)

Mesh axis order is (pp, dp, fsdp, cp, mp): mp innermost so TP collectives
ride the fastest ICI links, cp next so the KV ring permute stays on-chip
neighbors, pp outermost so stage p2p can cross DCN.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "MeshConfig",
    "build_mesh",
    "mesh_from_config",
    "use_mesh",
    "active_mesh",
    "ambient_mesh",
    "shard_map",
    "DATA_AXES",
    "get_data_world",
    "batch_sharding",
]

# Axes over which the batch dimension is sharded (data-parallel world =
# dp_degree * sharding_degree, matching reference env.py:121-141).
DATA_AXES = ("dp", "fsdp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Parallel-degree tuple (pp/dp/fsdp/cp/mp + sharding stage/offload)
    parsed from the Distributed config section."""
    dp: int = 1
    fsdp: int = 1
    mp: int = 1
    pp: int = 1
    cp: int = 1
    sharding_stage: int = 1
    sharding_offload: bool = False  # opt-state in host memory (pinned_host)

    @property
    def nranks(self) -> int:
        return self.dp * self.fsdp * self.mp * self.pp * self.cp

    @classmethod
    def from_dist_config(cls, dist) -> "MeshConfig":
        """Build from a normalized ``Distributed`` config section."""
        sharding = dist.get("sharding") or {}
        return cls(
            dp=dist.get("dp_degree") or 1,
            fsdp=sharding.get("sharding_degree") or 1,
            mp=dist.get("mp_degree") or 1,
            pp=dist.get("pp_degree") or 1,
            cp=dist.get("cp_degree") or 1,
            sharding_stage=sharding.get("sharding_stage") or 1,
            sharding_offload=bool(sharding.get("sharding_offload")),
        )


def build_mesh(
    cfg: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create the (pp, dp, fsdp, mp) mesh.

    Uses `jax.experimental.mesh_utils` device assignment on real TPU slices so
    axes map onto the physical torus; trivial reshape elsewhere (CPU tests).
    """
    if devices is None:
        devices = jax.devices()
    shape = (cfg.pp, cfg.dp, cfg.fsdp, cfg.cp, cfg.mp)
    if cfg.nranks < len(devices):
        devices = list(devices)[: cfg.nranks]  # sub-mesh of the first N
    if cfg.nranks != len(devices):
        raise ValueError(
            f"mesh {shape} needs {cfg.nranks} devices, have {len(devices)}"
        )
    if devices[0].platform == "tpu" and cfg.nranks > 1:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    else:
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, ("pp", "dp", "fsdp", "cp", "mp"))


def mesh_from_config(cfg, devices=None) -> Mesh:
    """Mesh straight from a full training config (its Distributed section)."""
    return build_mesh(MeshConfig.from_dist_config(cfg.get("Distributed") or {}), devices)


def get_data_world(mesh: Mesh) -> int:
    """dp*fsdp world size — number of distinct data shards."""
    return mesh.shape["dp"] * mesh.shape["fsdp"]


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for host-fed batches: batch dim over the data axes."""
    return NamedSharding(mesh, P(DATA_AXES))


# ------------------------------------------------------------- mesh context
# jax's legacy `with mesh:` context is only observable through the deprecated
# `pxla.thread_resources`; the modern `jax.sharding.get_mesh()` only sees
# meshes installed via `jax.sharding.set_mesh`. The framework keeps its own
# tiny registry so code deep inside a jitted model (ring attention,
# context_parallel.py) can find the mesh the Trainer entered without any
# deprecated API.

import contextlib
import contextvars

# context-local (so threaded servers with different meshes don't cross-talk,
# matching the thread-locality of jax's own mesh context)
_ACTIVE_MESHES: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "fleetx_active_meshes", default=()
)


def active_mesh() -> Optional[Mesh]:
    """Innermost mesh entered via :func:`use_mesh` (None outside)."""
    stack = _ACTIVE_MESHES.get()
    return stack[-1] if stack else None


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Enter a mesh for GSPMD lowering AND record it for framework lookups."""
    token = _ACTIVE_MESHES.set(_ACTIVE_MESHES.get() + (mesh,))
    try:
        with mesh:
            yield mesh
    finally:
        _ACTIVE_MESHES.reset(token)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` across the API move: newer jax exposes it at the
    top level with ``check_vma``; 0.4.x ships ``jax.experimental.shard_map``
    with the same knob spelled ``check_rep``. One call site contract
    (keyword mesh/in_specs/out_specs) for every framework user."""
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def ambient_mesh() -> Optional[Mesh]:
    """The mesh a model-interior ``shard_map`` should run over, best-effort:
    the modern jax context mesh (jax.sharding.set_mesh) first, then the
    framework's own registry (:func:`use_mesh` — what the Trainer enters).
    No deprecated thread_resources lookups. Used by ring attention
    (parallel/context_parallel.py) and the flash kernel's TP wrapper
    (ops/pallas/flash_attention.py)."""
    try:
        m = jax.sharding.get_mesh()  # set via jax.sharding.set_mesh
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    try:
        m = jax.sharding.get_abstract_mesh()
        if m is not None and not m.empty:  # pragma: no cover - version dependent
            return m
    except Exception:
        pass
    return active_mesh()
