"""Distributed environment + RNG-seed discipline.

Parity with reference env.py (/root/reference/ppfleetx/distributed/apis/
env.py:34-154): ``set_seed`` derives a *global* seed shared by all model-
parallel ranks (replicated tensors, e.g. attention dropout on replicated
activations must agree across mp) and a *local* per-rank component for
sharded tensors. In JAX the mechanism is key derivation rather than stateful
RNG trackers: one root key per run; dropout keys are derived by
``jax.random.fold_in`` of (root, step, data_rank) so they are invariant
across mp ranks by construction, and per-shard randomness comes from
folding in the shard index inside the sharded op itself.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from fleetx_tpu.utils.device_guard import honor_platform_env
from fleetx_tpu.utils.log import logger

__all__ = ["init_dist_env", "set_seed", "root_key", "global_seed", "data_rank_key"]

_ROOT_KEY = None
_GLOBAL_SEED = None


def init_dist_env(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host init. On a TPU pod slice, `jax.distributed.initialize()`
    discovers peers from the TPU metadata service; coordinator address /
    process count / process id are only needed on CPU/GPU clusters (or come
    from FLEETX_COORDINATOR / FLEETX_NUM_PROCESSES / FLEETX_PROCESS_ID).
    Single-process runs are a no-op.

    Replaces the reference's `fleet.init` + NCCL group construction
    (env.py:85-114) — there are no per-strategy process groups to build;
    the Mesh carries all topology.
    """
    # Honor an explicit JAX_PLATFORMS request even when a sitecustomize or
    # other early import already pinned a different platform.
    honor_platform_env()
    coordinator_address = coordinator_address or os.environ.get("FLEETX_COORDINATOR")
    if num_processes is None and os.environ.get("FLEETX_NUM_PROCESSES"):
        num_processes = int(os.environ["FLEETX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("FLEETX_PROCESS_ID"):
        process_id = int(os.environ["FLEETX_PROCESS_ID"])
    if coordinator_address or num_processes:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        logger.info(
            "distributed init: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )


def set_seed(seed: int) -> jax.Array:
    """Install the run's root PRNG key. Also seeds numpy/python for host-side
    shuffling (dataset index shuffles match the reference's
    np.random.RandomState(seed) usage)."""
    global _ROOT_KEY, _GLOBAL_SEED
    import numpy as np
    import random

    random.seed(seed)
    np.random.seed(seed % (2**32))
    _GLOBAL_SEED = seed
    _ROOT_KEY = jax.random.PRNGKey(seed)
    return _ROOT_KEY


def root_key() -> jax.Array:
    """The process-wide root PRNG key set by set_seed()."""
    if _ROOT_KEY is None:
        raise RuntimeError("call set_seed() first")
    return _ROOT_KEY


def global_seed() -> int:
    """The integer seed set_seed() was called with."""
    if _GLOBAL_SEED is None:
        raise RuntimeError("call set_seed() first")
    return _GLOBAL_SEED


def data_rank_key(step: int, data_rank: int = 0) -> jax.Array:
    """Dropout key for one train step of one data shard: invariant across
    mp/pp ranks (same fold-in inputs), distinct across steps and data ranks —
    the JAX analogue of the reference RNG-tracker global/local seed split
    (env.py:49-57)."""
    key = jax.random.fold_in(root_key(), step)
    return jax.random.fold_in(key, data_rank)
