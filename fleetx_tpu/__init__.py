"""fleetx_tpu — TPU-native large-model toolkit (JAX/XLA/Pallas/pjit).

Capability parity target: PaddleFleetX (see SURVEY.md). Idiomatic JAX:
one device mesh, GSPMD sharding rules, jitted train step, Pallas kernels.
"""

import os as _os

import jax as _jax

# Sharding-invariant PRNG. The legacy (non-partitionable) threefry lowering
# lets GSPMD produce DIFFERENT random bits depending on how the generating
# computation is partitioned — concretely, param init under a cp×mp mesh
# (4+ devices, transposed tile assignments) silently diverged from the
# single-device init (~1% first-step loss skew that looked like a ring-
# attention bug; see tests/test_cp_training.py::test_threefry_partitionable
# for the pinned-down repro). Partitionable threefry makes random values a
# pure function of (key, shape) regardless of mesh/sharding — the only
# sane semantics for a toolkit whose whole premise is "parallelism is a
# layout choice, not a math change". FLEETX_THREEFRY_PARTITIONABLE=0
# restores the legacy stream (e.g. to reproduce old checkpoints' inits).
if _os.environ.get("FLEETX_THREEFRY_PARTITIONABLE", "1") == "1":
    _jax.config.update("jax_threefry_partitionable", True)

__version__ = "0.1.0"
