"""fleetx_tpu — TPU-native large-model toolkit (JAX/XLA/Pallas/pjit).

Capability parity target: PaddleFleetX (see SURVEY.md). Idiomatic JAX:
one device mesh, GSPMD sharding rules, jitted train step, Pallas kernels.
"""

__version__ = "0.1.0"
