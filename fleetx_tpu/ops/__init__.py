"""Core tensor ops: attention dispatch, Pallas kernels, quantization."""
