"""Flash-decode: Pallas single-query attention over the kv cache.

The serving-side sibling of ops/pallas/flash_attention.py. During kv-cache
generation every step is one query token attending to the cache prefix
written so far — but the dense fallback streams the ENTIRE
``decode_cache_len`` buffer through HBM per step per layer, so a 1024-slot
cache costs 4x the traffic of a 256-token decode span. Decode attention is
purely bandwidth-bound (one [1, d] query does ~2*d FLOPs per cached key),
so HBM bytes touched IS the latency; this kernel makes those bytes scale
with the live prefix instead of the cache capacity.

Same idioms as the training kernel: online softmax (never materializes the
[1, cache_len] score row in HBM), major-block K/V streaming with an
in-kernel ``fori_loop`` over compute tiles, env-tunable block sizes, and
``interpret=True`` off-TPU so CPU tests execute the real kernel math.

What's different from the training kernel:
- q_len == 1: no causal structure inside a step. The valid key window per
  batch row is the contiguous ``[starts[b], end)`` — ``starts`` are the
  left-pad counts of the prompt (pads sit at the FRONT of the cache; see
  generation.py kv layout) and ``end`` is ``cache_index`` after this
  step's write (the query's own position + 1).
- ``end``/``starts`` are TRACED values (the loop counter of the decode
  ``while_loop``), so the dead-block skip cannot be a Python-level grid
  trim. They are fed through ``pltpu.PrefetchScalarGridSpec`` scalar
  prefetch: the K/V index maps clamp the streamed block index into the
  live ``[first, last]`` major-block range, so grid steps outside it
  repeat a resident index (NO HBM DMA) and ``pl.when`` retires them
  without compute. Per-step traffic is ceil(end/major) blocks — the
  tokens decoded so far — not ``cache_len``.
- forward-only: decode never differentiates, so there is no VJP, no lse
  output, and no dropout plumbing.

Int8 KV (``k_scale``/``v_scale`` given): K/V stream from HBM as int8 with
one fp32 scale per cached (row, head) vector (``ops/quant.quantize_kv``
layout, ``[..., cache_len, h, 1]`` scales). Dequantization happens in
VMEM inside the same online-softmax body — ``int8 -> f32 * scale`` per
resident tile, accumulator still fp32 — so the HBM bytes per decode step
roughly halve (8-bit K/V + 4 bytes of scale per head vector) while the
softmax math is bit-identical to dequantizing up front. The dense/XLA
fallback uses the same ``dequantize_kv`` helper, keeping every path on
one quantization contract (docs/QUANTIZATION.md).

Mesh-sharded decode (``mesh=`` on both entry points): under a TP/FSDP
serving mesh the KV cache lives head-sharded on ``mp``
(serving/engine.py "Mesh-sharded serving"), and a bare Pallas call over
sharded operands would make GSPMD replicate them — an all-gather of the
whole pool per step, defeating the kernel. Instead the call is wrapped
in ``shard_map`` over the local head slice: per-head online softmax is
independent across heads, so each device streams only ITS heads' live
prefix (the HBM-traffic contract holds per device) and the result is
bit-identical to the unsharded kernel. ``starts``/``ends`` and the
paged block tables are replicated; the logits all-gather happens only
at the row-parallel output projection GSPMD already manages.

Paged variant (:func:`flash_decode_paged_attention`): the serving engine's
page-granular cache stores K/V as ``[num_pages, page_size, h, d]`` shared
pages and each batch row addresses its logical window through a block
table of page indices (serving/cache_manager.py). The kernel body is THE
SAME online-softmax walk with ``major == page_size`` — only the K/V index
maps change: the per-row block table rides scalar prefetch next to
``starts``/``ends``, and grid step ``jm`` (the row's logical page index)
gathers physical page ``table[b, jm]`` instead of streaming block ``jm``
of a contiguous buffer. Dead steps still clamp into the live
``[first, last]`` logical range, so they repeat a resident physical page
and trigger no DMA; pages shared between rows (prefix reuse) are simply
gathered by several rows' tables.
"""

from __future__ import annotations

import functools
import os as _os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fleetx_tpu.ops.pallas.flash_attention import (
    NEG_INF,
    CompilerParams,
    _env_block,
    _interpret,
    _mm_dtype,
)

__all__ = [
    "flash_decode_attention",
    "flash_decode_paged_attention",
    "decode_flash_supported",
    "decode_mesh_shardable",
    "fit_decode_blocks",
    "paged_gather_kv",
]

# Cache-dim tile sizes, swept independently of the training kernel's
# (decode tiles trade MXU shape for DMA granularity — the query side is one
# row, so there is no q-block dimension to balance against).
DEFAULT_DECODE_BLOCK_K = _env_block("FLEETX_DECODE_BLOCK_K", 256)
# rows of K and V resident in VMEM per grid step (the HBM->VMEM DMA unit)
DEFAULT_DECODE_BLOCK_MAJOR = _env_block("FLEETX_DECODE_BLOCK_MAJOR", 1024)


def fit_decode_blocks(cache_len: int,
                      want_k: Optional[int] = None,
                      want_major: Optional[int] = None):
    """(block_k, major) tiling ``cache_len``, or (None, None) if no 8-row
    tile divides it. Largest divisor <= the requested sizes, mirroring
    flash_attention.fit_blocks. Trace-time Python only."""
    want_k = DEFAULT_DECODE_BLOCK_K if want_k is None else want_k
    want_major = DEFAULT_DECODE_BLOCK_MAJOR if want_major is None else want_major
    want_k = min(want_k, cache_len)
    block_k = next(
        (bk for bk in range(want_k - want_k % 8, 7, -8)
         if cache_len % bk == 0), None
    )
    if block_k is None:
        return None, None
    n = cache_len // block_k
    t = min(n, max(want_major // block_k, 1))
    while n % t:
        t -= 1
    return block_k, t * block_k


def decode_flash_supported(cache_len: int) -> bool:
    """Static dispatch check for the model layer: the cache tiles, and we
    are on a real TPU (or the interpreter is explicitly forced — CPU decode
    parity tests and the multichip dryrun set FLEETX_FORCE_FLASH=1)."""
    block_k, _ = fit_decode_blocks(cache_len)
    return block_k is not None and (
        jax.default_backend() in ("tpu", "axon")
        or _os.environ.get("FLEETX_FORCE_FLASH") == "1"
    )


def _data_extent(mesh) -> int:
    """dp*fsdp world of a mesh — the axes one-shot callers batch-shard
    activations (and decode caches) over."""
    sizes = dict(mesh.shape)
    return sizes.get("dp", 1) * sizes.get("fsdp", 1)


def decode_mesh_shardable(mesh, num_heads: int,
                          batch: Optional[int] = None) -> bool:
    """True when the decode kernels can run per-shard under ``mesh``
    (module docstring "Mesh-sharded decode"): no pp/cp extents (the
    shard_map's specs would treat those axes as replicated, all-gathering
    pipeline-stage or cp-sharded operands around the kernel), the
    attention heads must divide over the ``mp`` extent, and — when the
    mesh has dp/fsdp extents and the caller supplied ``batch`` — the
    batch must divide over them too. One-shot ``generate()`` under a
    data-parallel mesh keeps its cache batch-sharded over (dp, fsdp); a
    shard_map that replicated that axis would all-gather the whole cache
    per step (the exact pathology the old dense fallback avoided), so a
    non-dividing batch keeps the dense path. The per-head/per-row
    online-softmax walk is embarrassingly parallel, so a sliced kernel
    call is bit-identical to the unsharded one."""
    sizes = dict(mesh.shape)
    if sizes.get("pp", 1) > 1 or sizes.get("cp", 1) > 1:
        return False
    if num_heads % sizes.get("mp", 1):
        return False
    n_data = _data_extent(mesh)
    return n_data == 1 or batch is None or batch % n_data == 0


def _decode_specs(mesh, batch: Optional[int]):
    """(batch axes, operand spec) for the decode shard_map: heads on mp
    (all rank-4 operands — q, K/V slots or pages, and the [..., h, 1]
    scales — carry heads at axis 2), batch over (dp, fsdp) when
    ``batch`` is given and divides. Sharding a replicated operand
    merely slices it; the guard in :func:`decode_mesh_shardable` keeps
    the reverse (replicating a batch-sharded cache = a per-step
    all-gather) off this path. ``batch=None`` = never shard axis 0
    (the paged pools' page axis is shared by every row)."""
    from jax.sharding import PartitionSpec as P

    sizes = dict(mesh.shape)
    head = "mp" if sizes.get("mp", 1) > 1 else None
    data = tuple(a for a in ("dp", "fsdp") if sizes.get(a, 1) > 1)
    if batch is None or not batch or (data and batch % _data_extent(mesh)):
        data = ()  # direct callers without the guard: replicate batch
    batch_axes = data or None
    return batch_axes, P(batch_axes, None, head, None)


def _sharded_decode(mesh, starts_b, ends_b, operands, tables=None,
                    block_k=None, block_major=None):
    """shard_map both decode kernels over (heads -> mp; contiguous
    batch -> dp/fsdp when it divides). Without this, GSPMD treats the
    Pallas call as an opaque custom call and REPLICATES the sharded
    q/cache operands — an all-gather of the whole KV pool around the
    one kernel whose purpose is to bound HBM traffic (the PR 1
    "meshes -> dense XLA fallback" guard existed exactly because of
    that). The manual region hands each device its local slice;
    ``starts``/``ends`` follow the batch axes, and the per-row/per-head
    math is the unsharded kernel's bit-for-bit, so mesh serving keeps
    byte parity.

    ``operands`` is [q, k, v] (+ [k_scale, v_scale] at int8); ``tables``
    flips the paged variant on. Scale operands share the K/V head axis
    ([..., h, 1]), so one spec serves all five. Batch layouts differ:
    the CONTIGUOUS buffers carry batch at axis 0, matching one-shot
    ``generate()``'s dp/fsdp-sharded cache (:func:`decode_mesh_shardable`
    keeps non-dividing batches off this path); the PAGED pools carry
    PAGES at axis 0 — shared by every row's table — so the paged
    variant (serving-only, batch replicated by design) never shards it."""
    from jax.sharding import PartitionSpec as P

    from fleetx_tpu.parallel.mesh import shard_map

    if tables is None:
        batch_axes, spec = _decode_specs(mesh, operands[0].shape[0])

        def body(starts, ends, q, k, v, *scales):
            ks, vs = scales if scales else (None, None)
            return flash_decode_attention(
                q, k, v, end=ends, starts=starts, block_k=block_k,
                block_major=block_major, k_scale=ks, v_scale=vs)

        in_specs = (P(batch_axes), P(batch_axes)) + (spec,) * len(operands)
        fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=spec,
                       check_vma=False)
        return fn(starts_b, ends_b, *operands)

    _, spec = _decode_specs(mesh, None)  # heads-only: pool axis stays whole

    def pbody(starts, ends, tables, q, k, v, *scales):
        ks, vs = scales if scales else (None, None)
        return flash_decode_paged_attention(
            q, k, v, tables=tables, end=ends, starts=starts,
            block_k=block_k, k_scale=ks, v_scale=vs)

    in_specs = (P(None), P(None), P(None, None)) + (spec,) * len(operands)
    fn = shard_map(pbody, mesh=mesh, in_specs=in_specs, out_specs=spec,
                   check_vma=False)
    return fn(starts_b, ends_b, tables, *operands)


def _decode_kernel(starts_ref, ends_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, block_k: int, major: int,
                   scale: float, ks_ref=None, vs_ref=None):
    """Grid step (batch bi, head hi, K/V major block jm): online-softmax
    update of the single query row against the live tiles of the resident
    major block.

    Every tile intersecting ``[start, end)`` runs masked — with one query
    row the mask is a [1, block_k] compare, noise next to the two dots, so
    the training kernel's free/masked two-phase walk buys nothing here.

    ``ks_ref``/``vs_ref`` (int8 KV mode) are the per-vector fp32 scale
    blocks riding the same index map as K/V; each resident tile is
    dequantized in VMEM right before its dot product (module docstring)."""
    bi = pl.program_id(0)
    jm = pl.program_id(2)
    start = starts_ref[bi]
    end = ends_ref[bi]
    first_jm = start // major
    last_jm = (end - 1) // major
    tiles = major // block_k

    @pl.when(jm == first_jm)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when((jm >= first_jm) & (jm <= last_jm))
    def _step():
        mm_dt = _mm_dtype(q_ref.dtype)
        q = q_ref[:].astype(mm_dt)  # [1, d]
        # local tile range intersecting the valid window [start, end)
        t_lo = jnp.clip((start - jm * major) // block_k, 0, tiles)
        t_hi = jnp.clip(
            (end - jm * major + block_k - 1) // block_k, 0, tiles
        )

        def body(t, carry):
            m, l, acc = carry
            k_blk = k_ref[pl.ds(t * block_k, block_k), :]
            v_blk = v_ref[pl.ds(t * block_k, block_k), :]
            if ks_ref is not None:
                # dequant-in-VMEM: int8 tile * per-vector fp32 scale —
                # [block_k, d] * [block_k, 1]; HBM only ever saw int8
                k_blk = (k_blk.astype(jnp.float32)
                         * ks_ref[pl.ds(t * block_k, block_k), :])
                v_blk = (v_blk.astype(jnp.float32)
                         * vs_ref[pl.ds(t * block_k, block_k), :])
            k_blk = k_blk.astype(mm_dt)
            v_blk = v_blk.astype(mm_dt)
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [1, block_k]
            k_row = (jm * major + t * block_k
                     + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            s = jnp.where((k_row >= start) & (k_row < end), s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            # keep p exactly 0 on masked lanes so poisoned/unwritten cache
            # slots inside a boundary tile cannot leak through p @ v
            p = jnp.where(s > NEG_INF / 2, p, 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = alpha * acc + jax.lax.dot_general(
                p.astype(mm_dt), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        carry = (m_scr[:], l_scr[:], acc_scr[:])
        m, l, acc = jax.lax.fori_loop(t_lo, t_hi, body, carry)
        m_scr[:] = m
        l_scr[:] = l
        acc_scr[:] = acc

    @pl.when(jm == last_jm)
    def _finalize():
        l = l_scr[:]
        # the window always holds the query's own position, so l > 0; the
        # guard keeps a (contract-violating) empty window finite, not NaN
        l_safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_kernel_q8(starts_ref, ends_ref, q_ref, k_ref, v_ref, ks_ref,
                      vs_ref, o_ref, m_scr, l_scr, acc_scr, *, block_k: int,
                      major: int, scale: float):
    """Int8-KV grid step: the contiguous kernel body with the two scale
    operands threaded in (they ride the K/V index map, so a dead block's
    scales are as DMA-free as its values)."""
    _decode_kernel(starts_ref, ends_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, block_k=block_k, major=major,
                   scale=scale, ks_ref=ks_ref, vs_ref=vs_ref)


def _kv_index_map(major: int):
    """K/V major-block index for grid step (bi, hi, jm): clamped into the
    live [first, last] range of THIS batch row, so dead steps repeat a
    resident block index and trigger no DMA — the per-step HBM traffic is
    what scales with the decoded prefix. Blocks index the NATIVE
    [b, cache_len, h, d] cache layout: a [b*h, ...] repack would stream
    the entire cache through HBM once per step just to transpose it,
    costing more than the dense path it replaces."""

    def index_map(bi, hi, jm, starts_ref, ends_ref):
        first = starts_ref[bi] // major
        last = (ends_ref[bi] - 1) // major
        return bi, jnp.clip(jm, first, last), hi, 0

    return index_map


def _q_index_map(bi, hi, jm, starts_ref, ends_ref):
    return bi, 0, hi, 0


def flash_decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    end: jax.Array,
    starts: Optional[jax.Array] = None,
    block_k: Optional[int] = None,
    block_major: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    mesh=None,
) -> jax.Array:
    """Single-query attention against the kv cache, [b, 1, h, d] layout.

    ``k``/``v`` are the FULL cache buffers [b, cache_len, h, d]; ``end``
    (traced int32 scalar or [b]) is the number of live cache positions —
    ``cache_index`` after this step's write — and ``starts`` ([b] int32,
    optional) the per-row first valid position (left-pad count). Row b
    attends exactly the window [starts[b], end). No scaling/softmax state
    leaves the kernel; output dtype follows ``q``.

    ``k_scale``/``v_scale`` ([b, cache_len, h, 1] fp32, given together)
    switch the kernel to int8-KV mode: ``k``/``v`` are int8 per
    ``ops/quant.quantize_kv`` and each streamed tile is dequantized in
    VMEM (module docstring).

    ``cache_len`` must be a multiple of 8 (checked; callers pre-screen with
    :func:`decode_flash_supported` and take the XLA path otherwise).

    ``mesh`` invokes the kernel per-shard inside ``shard_map`` over the
    local head slice (:func:`_sharded_decode`): heads split on ``mp``,
    scalars/tables replicated — callers pre-screen with
    :func:`decode_mesh_shardable`.
    """
    b, sq, h, d = q.shape
    if sq != 1:
        raise ValueError(f"flash decode is single-query (q_len={sq})")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("int8 KV needs BOTH k_scale and v_scale")
    if mesh is not None and mesh.size > 1:
        ends_b = jnp.broadcast_to(jnp.asarray(end, jnp.int32), (b,))
        starts_b = (jnp.zeros((b,), jnp.int32) if starts is None
                    else starts.astype(jnp.int32))
        ops = [q, k, v] + ([k_scale, v_scale] if k_scale is not None else [])
        return _sharded_decode(mesh, starts_b, ends_b, ops,
                               block_k=block_k, block_major=block_major)
    cache_len = k.shape[1]
    block_k, major = fit_decode_blocks(cache_len, block_k, block_major)
    if block_k is None:
        raise ValueError(
            f"cache_len {cache_len} not tileable (must be a multiple of 8)"
        )
    n_major = cache_len // major

    ends_b = jnp.broadcast_to(jnp.asarray(end, jnp.int32), (b,))
    starts_b = (jnp.zeros((b,), jnp.int32) if starts is None
                else starts.astype(jnp.int32))

    # grid (b, h, majors) over the NATIVE [b, s, h, d] layout — no
    # [b*h, s, d] repack, which would itself stream the full cache
    kv_spec = pl.BlockSpec((None, major, None, d), _kv_index_map(major))
    in_specs = [pl.BlockSpec((None, 1, None, d), _q_index_map),
                kv_spec, kv_spec]
    operands = [q, k, v]
    if k_scale is not None:
        # scales ride the SAME clamped index map: a dead grid step repeats
        # resident scale blocks exactly like resident K/V blocks (no DMA)
        s_spec = pl.BlockSpec((None, major, None, 1), _kv_index_map(major))
        in_specs += [s_spec, s_spec]
        operands += [k_scale, v_scale]
        kernel = functools.partial(
            _decode_kernel_q8, block_k=block_k, major=major,
            scale=1.0 / (d**0.5))
    else:
        kernel = functools.partial(
            _decode_kernel, block_k=block_k, major=major,
            scale=1.0 / (d**0.5))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, n_major),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, 1, None, d), _q_index_map),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),   # running max m
            pltpu.VMEM((1, 1), jnp.float32),   # running normalizer l
            pltpu.VMEM((1, d), jnp.float32),   # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), q.dtype),
        compiler_params=CompilerParams(
            # the major-block axis carries the online-softmax scratch state
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(starts_b, ends_b, *operands)


# ------------------------------------------------------------- paged variant


def _paged_decode_kernel(starts_ref, ends_ref, tables_ref, q_ref, k_ref,
                         v_ref, o_ref, m_scr, l_scr, acc_scr, *, block_k: int,
                         page_size: int, scale: float):
    """Grid step (bi, hi, jm) where ``jm`` is row bi's LOGICAL page index:
    the block-table gather happens entirely in the K/V index maps, so the
    online-softmax body is the contiguous kernel's with major=page_size
    (``k_row`` below is the logical position jm*page_size + offset, which
    the index map made physically resident)."""
    del tables_ref  # consumed by the index maps, not the body
    _decode_kernel(starts_ref, ends_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, block_k=block_k, major=page_size,
                   scale=scale)


def _paged_decode_kernel_q8(starts_ref, ends_ref, tables_ref, q_ref, k_ref,
                            v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr,
                            acc_scr, *, block_k: int, page_size: int,
                            scale: float):
    """Int8-KV paged grid step: scale pages gather through the same block
    table as the K/V pages, dequant happens tile-by-tile in VMEM."""
    del tables_ref  # consumed by the index maps, not the body
    _decode_kernel(starts_ref, ends_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, block_k=block_k, major=page_size,
                   scale=scale, ks_ref=ks_ref, vs_ref=vs_ref)


def _paged_kv_index_map(page_size: int):
    """Physical-page index for grid step (bi, hi, jm): the row's block
    table translates the LOGICAL page index jm into a physical page of the
    ``[num_pages, page_size, h, d]`` pool; jm is first clamped into the
    row's live logical range so dead steps re-address a resident page (no
    DMA), exactly like the contiguous kernel's clamp."""

    def index_map(bi, hi, jm, starts_ref, ends_ref, tables_ref):
        first = starts_ref[bi] // page_size
        last = (ends_ref[bi] - 1) // page_size
        return tables_ref[bi, jnp.clip(jm, first, last)], 0, hi, 0

    return index_map


def _paged_q_index_map(bi, hi, jm, starts_ref, ends_ref, tables_ref):
    return bi, 0, hi, 0


def paged_gather_kv(pages: jax.Array, tables: jax.Array) -> jax.Array:
    """Dense-fallback gather: materialize each row's logical K/V buffer
    ``[b, logical_len, h, d]`` from the shared page pool
    ``[num_pages, page_size, h, d]`` via its block table ``[b, n_pages]``.

    The XLA parity path off-TPU (and for multi-token prefill, custom
    masks, meshes): it streams one logical cache's worth of HBM per call —
    the same traffic the contiguous dense fallback pays — so correctness
    fallbacks cost what they always cost, while the paged flash kernel
    above never materializes this buffer."""
    b, n_pages = tables.shape
    gathered = pages[tables]  # [b, n_pages, page_size, h, d]
    return gathered.reshape(b, n_pages * pages.shape[1], *pages.shape[2:])


def flash_decode_paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    *,
    tables: jax.Array,
    end: jax.Array,
    starts: Optional[jax.Array] = None,
    block_k: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    mesh=None,
) -> jax.Array:
    """Single-query attention against a PAGED kv cache.

    ``k_pages``/``v_pages`` are the shared page pools
    ``[num_pages, page_size, h, d]``; ``tables`` ([b, n_pages_per_row]
    int32) maps each row's logical page index to its physical page, and
    ``end`` ([b] or scalar int32, traced) is the row's live logical length
    (its window is ``[starts[b], end[b])`` in LOGICAL positions). Rows
    sharing prefix pages simply carry the same physical indices in their
    tables — the kernel reads shared pages like any other.

    ``k_scale``/``v_scale`` ([num_pages, page_size, h, 1] fp32, given
    together) switch to int8-KV mode: the pools are int8 per
    ``ops/quant.quantize_kv`` and scale pages gather through the same
    block table, dequantized in VMEM (module docstring).

    ``page_size`` must be a multiple of 8 (callers pre-screen with
    :func:`decode_flash_supported` on the page size); ``block_k`` tiles
    within a page (largest divisor wins, as in the contiguous kernel).
    ``mesh`` runs the kernel per-shard over the local head slice of the
    page pools (tables replicated) — see :func:`flash_decode_attention`.
    """
    b, sq, h, d = q.shape
    if sq != 1:
        raise ValueError(f"flash decode is single-query (q_len={sq})")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("int8 KV needs BOTH k_scale and v_scale")
    if mesh is not None and mesh.size > 1:
        ends_b = jnp.broadcast_to(jnp.asarray(end, jnp.int32), (b,))
        starts_b = (jnp.zeros((b,), jnp.int32) if starts is None
                    else starts.astype(jnp.int32))
        ops = ([q, k_pages, v_pages]
               + ([k_scale, v_scale] if k_scale is not None else []))
        return _sharded_decode(mesh, starts_b, ends_b, ops,
                               tables=tables.astype(jnp.int32),
                               block_k=block_k)
    page_size = k_pages.shape[1]
    # major is pinned to one page (the gather unit); block_k tiles inside
    block_k, major = fit_decode_blocks(page_size, block_k, page_size)
    if block_k is None or major != page_size:
        raise ValueError(
            f"page_size {page_size} not tileable (must be a multiple of 8)"
        )
    n_logical = tables.shape[1]

    ends_b = jnp.broadcast_to(jnp.asarray(end, jnp.int32), (b,))
    starts_b = (jnp.zeros((b,), jnp.int32) if starts is None
                else starts.astype(jnp.int32))
    tables_b = tables.astype(jnp.int32)

    kv_spec = pl.BlockSpec((None, page_size, None, d),
                           _paged_kv_index_map(page_size))
    in_specs = [pl.BlockSpec((None, 1, None, d), _paged_q_index_map),
                kv_spec, kv_spec]
    operands = [q, k_pages, v_pages]
    if k_scale is not None:
        s_spec = pl.BlockSpec((None, page_size, None, 1),
                              _paged_kv_index_map(page_size))
        in_specs += [s_spec, s_spec]
        operands += [k_scale, v_scale]
        kernel = functools.partial(
            _paged_decode_kernel_q8, block_k=block_k, page_size=page_size,
            scale=1.0 / (d**0.5))
    else:
        kernel = functools.partial(
            _paged_decode_kernel, block_k=block_k, page_size=page_size,
            scale=1.0 / (d**0.5))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, h, n_logical),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, 1, None, d), _paged_q_index_map),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),   # running max m
            pltpu.VMEM((1, 1), jnp.float32),   # running normalizer l
            pltpu.VMEM((1, d), jnp.float32),   # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), q.dtype),
        compiler_params=CompilerParams(
            # the logical-page axis carries the online-softmax scratch state
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(starts_b, ends_b, tables_b, *operands)
