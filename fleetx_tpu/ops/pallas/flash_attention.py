"""Flash attention — Pallas TPU kernels with custom VJP.

The TPU replacement for the reference's fused CUDA softmax-mask kernel +
score-matrix attention (/root/reference/ppfleetx/models/language_model/gpt/
dygraph/single_model.py:216-240 ``core_attn`` +
``incubate.softmax_mask_fuse_upper_triangle``): online-softmax tiling keeps
the [s, s] score matrix out of HBM entirely, so long sequences don't need the
reference's ``recompute_granularity=core_attn`` memory workaround.

Two masking modes, both resolved inside the kernels:
- ``causal=True``: lower-triangular (GPT decoders); k-block scan stops at
  the diagonal.
- ``kv_lens`` (optional, [batch] int32): right-padding key mask — position
  k attends only if ``k < kv_lens[b]``. This is the contiguous-padding
  form of the reference encoder's ``attention_mask`` (ernie single_model
  builds it from ``input_ids != pad``), so bidirectional ERNIE-style
  encoders ride the flash path too (``causal=False`` + kv_lens).

Attention dropout runs *inside* the kernel: a counter-based integer hash
(lowbias32 finalizer) of (seed, batch*head, q_pos, k_pos) produces the keep
mask, so the backward kernels regenerate the identical mask from the same
seed with zero extra HBM traffic — the reference reaches the same
determinism via its CUDA RNG tracker ``local_seed``
(/root/reference/ppfleetx/distributed/apis/env.py:49-54). The hash is plain
int32 arithmetic, so the kernel behaves identically under the Pallas
interpreter on CPU (where pltpu.prng_* has no lowering) and on real TPUs.

Layout: q, k, v are [batch, seq, heads, head_dim] (model layout); kernels run
per (batch*head) over q-row blocks, scanning k-column blocks up to the causal
diagonal (or the full row when non-causal). fp32 accumulation, inputs any
float dtype.

Regime note: each program holds one full K/V row in VMEM (2 * seq *
head_dim * 4B), which caps per-device sequence around ~8-16k at head_dim
64-128 on 16 MiB-VMEM parts. Long-context training shards sequence over
the cp axis first (parallel/context_parallel.py ring attention), so the
per-device slice stays inside this envelope; lifting the cap entirely
(grid-streamed K blocks with Pallas-pipelined HBM loads) is the next
kernel iteration.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

import os as _os

# overridable without code changes so block sizes can be swept per TPU
# generation (bench harness: FLEETX_FLASH_BLOCK_Q=256 python bench.py)
DEFAULT_BLOCK_Q = int(_os.environ.get("FLEETX_FLASH_BLOCK_Q", 128))
DEFAULT_BLOCK_K = int(_os.environ.get("FLEETX_FLASH_BLOCK_K", 128))
NEG_INF = -1e30

# lowbias32 mixing constants (public-domain integer hash); stored as wrapped
# int32 because Pallas TPU integer math is int32.
_MIX1 = np.int32(np.uint32(0x7FEB352D))
_MIX2 = np.int32(np.uint32(0x846CA68B))
_C1 = np.int32(np.uint32(0x9E3779B1))
_C2 = np.int32(np.uint32(0x85EBCA77))
_C3 = np.int32(np.uint32(0xC2B2AE3D))


def _interpret() -> bool:
    """Pallas interpreter mode off-TPU (CPU tests of kernel math)."""
    return jax.default_backend() not in ("tpu", "axon")


def _shr(x, n):
    return jax.lax.shift_right_logical(x, jnp.int32(n))


def dropout_keep_scale(seed, bh, q_pos, k_pos, rate: float):
    """Deterministic dropout scale in {0, 1/(1-rate)} for each (q, k) cell.

    seed: int32 scalar; bh: int32 scalar batch*head index; q_pos/k_pos: int32
    grids of global positions (any broadcast-compatible shapes). Pure int32
    jnp ops so forward/backward kernels (and test references) can regenerate
    the exact mask.
    """
    x = q_pos * _C1 + k_pos * _C2 + bh * _C3 + seed
    x = x ^ _shr(x, 16)
    x = x * _MIX1
    x = x ^ _shr(x, 15)
    x = x * _MIX2
    x = x ^ _shr(x, 16)
    # 31 uniform bits; drop iff below the threshold.
    threshold = jnp.int32(int(rate * (1 << 31)))
    keep = (x & jnp.int32(0x7FFFFFFF)) >= threshold
    return keep.astype(jnp.float32) / (1.0 - rate)


def _score_mask(q_pos, k_pos, kvlen, causal: bool):
    """Bool mask for a score tile: causal triangle ∧ key inside kv_lens."""
    mask = k_pos < kvlen
    if causal:
        mask &= q_pos >= k_pos
    return mask


def _fwd_kernel(seed_ref, kvlens_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                *, block_k: int, scale: float, dropout_rate: float,
                causal: bool, seq_len: int):
    """One (batch*head, q-block) program: online softmax over k blocks."""
    bq, d = q_ref.shape
    bh = pl.program_id(0)
    i = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    kvlen = kvlens_ref[bh]

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, block_k]
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        s = jnp.where(_score_mask(q_pos, k_pos, kvlen, causal), s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # fully-masked rows: keep p exactly 0 (avoids exp(NEG-NEG)=1 garbage
        # rows feeding dV through p in the backward kernels)
        p = jnp.where(s > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m - m_new)
        # The softmax normalizer sums the *undropped* probabilities; dropout
        # scales only the value-weighted path (out = dropout(softmax(s)) @ v).
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            p = p * dropout_keep_scale(seed_ref[0], bh, q_pos, k_pos, dropout_rate)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    # causal: only k blocks at or before this q block contribute
    # (block_q % block_k == 0 enforced at dispatch)
    num_k_blocks = (i + 1) * bq // block_k if causal else seq_len // block_k
    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))

    l_safe = jnp.where(l > 0.0, l, 1.0)  # fully-masked rows emit zeros
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l_safe)  # [bq, 1] tile of the (bh, s, 1) array


def _bwd_dq_kernel(seed_ref, kvlens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, block_k: int, scale: float,
                   dropout_rate: float, causal: bool, seq_len: int):
    bq, d = q_ref.shape
    bh = pl.program_id(0)
    i = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]      # [bq, 1]
    delta = delta_ref[:]  # [bq, 1]
    kvlen = kvlens_ref[bh]
    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, dq):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = _score_mask(q_pos, k_pos, kvlen, causal)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            # dP = (dO @ V^T) ∘ mask; delta already equals rowsum(P ∘ dP)
            # because delta = rowsum(dO ∘ O) and O = (P ∘ mask) @ V.
            dp = dp * dropout_keep_scale(seed_ref[0], bh, q_pos, k_pos, dropout_rate)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    num_k_blocks = (i + 1) * bq // block_k if causal else seq_len // block_k
    dq = jax.lax.fori_loop(0, num_k_blocks, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, kvlens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *, block_q: int, scale: float,
                    seq_len: int, dropout_rate: float, causal: bool):
    bk, d = k_ref.shape
    bh = pl.program_id(0)
    j = pl.program_id(1)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    kvlen = kvlens_ref[bh]
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
    # causal: only q blocks at/after this k block see it; non-causal: all
    first_q_block = j * bk // block_q if causal else 0

    def body(ii, carry):
        dk, dv = carry
        i = first_q_block + ii
        q_blk = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32) * scale
        do_blk = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :]      # [block_q, 1]
        delta = delta_ref[pl.ds(i * block_q, block_q), :]  # [block_q, 1]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        mask = _score_mask(q_pos, k_pos, kvlen, causal)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            drop = dropout_keep_scale(seed_ref[0], bh, q_pos, k_pos, dropout_rate)
            p_v = p * drop  # dropped probabilities feed dV
            dp = dp * drop
        else:
            p_v = p
        dv = dv + jax.lax.dot_general(
            p_v, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    n_iter = seq_len // block_q - first_q_block
    dk, dv = jax.lax.fori_loop(
        0, n_iter, body, (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32))
    )
    # q blocks were loaded pre-scaled, so the chain rule's `scale` factor is
    # already inside `ds @ q_scaled`
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _to_bh(x):
    """[b, s, h, d] -> [b*h, s, d]"""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bh(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _seed_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _fwd_call(seed, kvlens, q3, k3, v3, block_q, block_k, scale, dropout_rate,
              causal):
    bh, s, d = q3.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, scale=scale, dropout_rate=dropout_rate,
        causal=causal, seq_len=s,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _seed_spec(),
            _seed_spec(),
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            # trailing singleton dim: Mosaic requires the last block dim to
            # divide 128 or equal the array dim — (block_q, 1) satisfies it
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(seed, kvlens, q3, k3, v3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, seed, kvlens, block_q, block_k, dropout_rate, causal):
    out, _ = _flash_fwd(q, k, v, seed, kvlens, block_q, block_k, dropout_rate,
                        causal)
    return out


def _flash_fwd(q, k, v, seed, kvlens, block_q, block_k, dropout_rate, causal):
    b, s, h, d = q.shape
    scale = 1.0 / (d**0.5)
    q3, k3, v3 = _to_bh(q), _to_bh(k), _to_bh(v)
    o3, lse = _fwd_call(
        seed, kvlens, q3, k3, v3, block_q, block_k, scale, dropout_rate, causal
    )
    return _from_bh(o3, b, h), (q3, k3, v3, o3, lse, seed, kvlens, b, h)


def _flash_bwd(block_q, block_k, dropout_rate, causal, res, g):
    q3, k3, v3, o3, lse, seed, kvlens, b, h = res
    bh, s, d = q3.shape
    scale = 1.0 / (d**0.5)
    do3 = _to_bh(g)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [bh, s, 1]

    dq3 = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, scale=scale,
            dropout_rate=dropout_rate, causal=causal, seq_len=s,
        ),
        grid=(bh, s // block_q),
        in_specs=[
            _seed_spec(),
            _seed_spec(),
            pl.BlockSpec((None, block_q, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((None, s, d), lambda b_, i: (b_, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b_, i: (b_, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b_, i: (b_, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b_, i: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        interpret=_interpret(),
    )(seed, kvlens, q3, k3, v3, do3, lse, delta)

    dk3, dv3 = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, scale=scale, seq_len=s,
            dropout_rate=dropout_rate, causal=causal,
        ),
        grid=(bh, s // block_k),
        in_specs=[
            _seed_spec(),
            _seed_spec(),
            pl.BlockSpec((None, s, d), lambda b_, j: (b_, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda b_, j: (b_, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b_, j: (b_, j, 0)),
            pl.BlockSpec((None, s, d), lambda b_, j: (b_, 0, 0)),
            pl.BlockSpec((None, s, 1), lambda b_, j: (b_, 0, 0)),
            pl.BlockSpec((None, s, 1), lambda b_, j: (b_, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b_, j: (b_, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b_, j: (b_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v3.dtype),
        ],
        interpret=_interpret(),
    )(seed, kvlens, q3, k3, v3, do3, lse, delta)

    dq = _from_bh(dq3, b, h)
    dk = _from_bh(dk3, b, h)
    dv = _from_bh(dv3, b, h)
    # seed/kvlens are integer-dtype: their cotangent type is float0
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    dkvlens = np.zeros(kvlens.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dseed, dkvlens


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    *,
    causal: bool = True,
    kv_lens: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Flash attention, [b, s, h, d] layout. Sequence length must be a
    multiple of the block sizes (callers fall back to the XLA path
    otherwise — fleetx_tpu/ops/attention.py). ``kv_lens`` [b] int32 masks
    right-padded keys (position k valid iff k < kv_lens[b]); ``causal=False``
    gives bidirectional (encoder) attention. ``dropout_rate > 0`` requires a
    ``dropout_rng`` key; the mask is generated inside the kernel."""
    b, s, h, _ = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k or block_q % block_k:
        raise ValueError(f"seq {s} not tileable by ({block_q}, {block_k})")
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        seed = jax.random.bits(dropout_rng, (1,), "uint32").astype(jnp.int32)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    if kv_lens is None:
        kvlens_bh = jnp.full((b * h,), s, jnp.int32)
    else:
        kvlens_bh = jnp.repeat(kv_lens.astype(jnp.int32), h)  # [b*h]
    return _flash(q, k, v, seed, kvlens_bh, block_q, block_k,
                  float(dropout_rate), bool(causal))
