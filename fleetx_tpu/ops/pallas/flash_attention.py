"""Flash attention — Pallas TPU kernels with custom VJP.

The TPU replacement for the reference's fused CUDA softmax-mask kernel +
score-matrix attention (/root/reference/ppfleetx/models/language_model/gpt/
dygraph/single_model.py:216-240 ``core_attn`` +
``incubate.softmax_mask_fuse_upper_triangle``): online-softmax tiling keeps
the [s, s] score matrix out of HBM entirely, so long sequences don't need the
reference's ``recompute_granularity=core_attn`` memory workaround.

Two masking modes, both resolved inside the kernels:
- ``causal=True``: lower-triangular (GPT decoders); k blocks past the
  diagonal are skipped.
- ``kv_lens`` (optional, [batch] int32): right-padding key mask — position
  k attends only if ``k < kv_lens[b]``. This is the contiguous-padding
  form of the reference encoder's ``attention_mask`` (ernie single_model
  builds it from ``input_ids != pad``), so bidirectional ERNIE-style
  encoders ride the flash path too (``causal=False`` + kv_lens).

Attention dropout runs *inside* the kernel with zero extra HBM traffic —
the reference reaches the same determinism via its CUDA RNG tracker
``local_seed`` (/root/reference/ppfleetx/distributed/apis/env.py:49-54).
Two deterministic bit sources:
- default (every backend): a counter-based integer hash (lowbias32
  finalizer) of (seed, GLOBAL batch*head, q_pos, k_pos) — plain int32
  arithmetic the host-side tests reproduce bit-for-bit, and
  layout-invariant across dp/mp/cp shardings by construction;
- ``FLEETX_FLASH_HW_RNG=1`` opt-in (real TPUs): the hardware PRNG
  (``pltpu.prng_seed/prng_random_bits``), seeded per (seed, batch*head,
  q-tile, k-tile). Cheaper per tile, but keyed on TILE ids — only
  self-consistent between identically-tiled kernels, and unverified on
  hardware until the TPU-gated test_hw_rng_* suite passes on a live chip
  (ADVICE r4); flip the default only then. Either source must be held
  fixed for the life of a training run (checkpoints record it).

Layout: q, k, v are [batch, seq, heads, head_dim] (model layout).

Major-block streaming (round-4, second iteration). Two regimes were tried:
whole-row K/V residency (rounds 1-3) caps per-device sequence at ~8-16k
tokens; one-grid-step-per-128-tile streaming (round 4, first cut) lifted the
cap but regressed 1k-seq MFU 23%→15% — per-grid-step overhead swamps the
~4 MFLOP a 128x128 online-softmax update does. This version does both:
the grid's innermost axis streams K/V (or Q for the dK/dV kernel) in
*major* blocks of FLEETX_FLASH_BLOCK_MAJOR rows (default 1024), and an
in-kernel ``fori_loop`` walks the compute tiles inside the resident major
block with an exact causal trip count. VMEM holds one major block per
streamed operand (seq-independent; Mosaic double-buffers the stream), and
at seq <= the major size the grid degenerates to one step per (bh, q-block)
— the exact structure that measured MFU 23% at 1k seq. Causal skipping:
- the streamed operand's index_map clamps at the diagonal, so skipped grid
  steps repeat a block index and are NOT re-fetched (no HBM traffic);
- ``pl.when`` guards the compute, so skipped steps retire immediately;
- inside a live step the fori_loop trip count covers exactly the tiles at
  or before the diagonal.
The innermost grid axis is sequential on TPU ("arbitrary" dimension
semantics), which is what makes the scratch carry across major steps valid;
(batch*head, fixed-block) are marked parallel for megacore partitioning.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

import os as _os

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; accept either
# so the kernels load on both sides of the rename
CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _env_block(name: str, default: int) -> int:
    """Env-tunable block size; validated once at import (ADVICE r3 #4:
    a 0/negative override used to surface as ZeroDivisionError at dispatch)."""
    raw = _os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError as e:
        raise ValueError(f"{name}={raw!r} is not an integer") from e
    if val <= 0 or val % 8:
        # blocks tile the (second-to-last) sequence dim, so only sublane (8)
        # alignment is required — head_dim carries the 128-lane constraint
        raise ValueError(
            f"{name}={val} invalid: block sizes must be positive multiples "
            "of the 8-row TPU sublane tile"
        )
    return val


# overridable without code changes so block sizes can be swept per TPU
# generation (bench harness: FLEETX_FLASH_BLOCK_Q=256 python bench.py).
# 512x512 default from the round-4 v5e sweep: at 345M/seq1024/b8 it measured
# 23.8k tok/s vs 18.1k at 128x128 (the per-cell VPU work of online softmax
# amortizes over bigger tiles, and fewer grid steps means less fixed
# overhead); 1024x512 regressed (megacore q-block parallelism lost).
DEFAULT_BLOCK_Q = _env_block("FLEETX_FLASH_BLOCK_Q", 512)
DEFAULT_BLOCK_K = _env_block("FLEETX_FLASH_BLOCK_K", 512)
# rows of the streamed operand resident in VMEM per grid step (the unit of
# HBM->VMEM DMA); compute tiles walk inside it
DEFAULT_BLOCK_MAJOR = _env_block("FLEETX_FLASH_BLOCK_MAJOR", 1024)
if DEFAULT_BLOCK_Q % DEFAULT_BLOCK_K:
    # the dispatch-time tileability check requires block_k | block_q; catch
    # a bad override pair at import instead of silently routing every call
    # to the XLA fallback
    raise ValueError(
        f"FLEETX_FLASH_BLOCK_Q={DEFAULT_BLOCK_Q} must be a multiple of "
        f"FLEETX_FLASH_BLOCK_K={DEFAULT_BLOCK_K}"
    )
NEG_INF = -1e30

# lowbias32 mixing constants (public-domain integer hash); stored as wrapped
# int32 because Pallas TPU integer math is int32.
_MIX1 = np.int32(np.uint32(0x7FEB352D))
_MIX2 = np.int32(np.uint32(0x846CA68B))
_C1 = np.int32(np.uint32(0x9E3779B1))
_C2 = np.int32(np.uint32(0x85EBCA77))
_C3 = np.int32(np.uint32(0xC2B2AE3D))


def _interpret() -> bool:
    """Pallas interpreter mode off-TPU (CPU tests of kernel math)."""
    return jax.default_backend() not in ("tpu", "axon")


def _compiler_params():
    # innermost grid axis carries the online-softmax scratch state, so it
    # must stay sequential; the outer two can partition over megacores
    return CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _shr(x, n):
    return jax.lax.shift_right_logical(x, jnp.int32(n))


def dropout_keep_scale(seed, bh, q_pos, k_pos, rate: float):
    """Deterministic dropout scale in {0, 1/(1-rate)} for each (q, k) cell.

    seed: int32 scalar; bh: int32 scalar batch*head index; q_pos/k_pos: int32
    grids of global positions (any broadcast-compatible shapes). Pure int32
    jnp ops so forward/backward kernels (and test references) can regenerate
    the exact mask. Grouped so that when callers pass a [bq, 1] q column and
    a [1, bk] k row, the multiplies stay on the vectors (int32 multiply is
    multi-op on the VPU) and only the combine + mix rounds touch the full
    [bq, bk] tile; int32 + is modular, so the grouping does not change the
    hash value vs the original flat expression.
    """
    x = (q_pos * _C1 + (bh * _C3 + seed)) + k_pos * _C2
    x = x ^ _shr(x, 16)
    x = x * _MIX1
    x = x ^ _shr(x, 15)
    x = x * _MIX2
    x = x ^ _shr(x, 16)
    # 31 uniform bits; drop iff below the threshold.
    threshold = jnp.int32(int(rate * (1 << 31)))
    keep = (x & jnp.int32(0x7FFFFFFF)) >= threshold
    return keep.astype(jnp.float32) / (1.0 - rate)


# FLEETX_FLASH_HW_RNG=1 switches real-TPU dropout bits to the hardware
# PRNG (pltpu.prng_*); the default is the lowbias32 hash on every backend.
# Default OFF (ADVICE r4 medium): the HW path assumes bit-layout agreement
# across the three separately-compiled kernels, which only the TPU-gated
# test_hw_rng_* tests can certify — and they have not yet run on a live
# chip. Flip the default only after they pass on hardware. Either source
# must be held constant across a training run: the realized masks differ,
# so toggling mid-run (or resuming on the other setting) changes the
# noise stream.
HW_RNG = _os.environ.get("FLEETX_FLASH_HW_RNG", "0") == "1"


def _tile_keep_scale(seed, bh, qb, kb, q_col, k_row, shape, rate: float,
                     hw_rng: bool = True):
    """Dropout keep/scale for one [block_q, block_k] score tile.

    seed/bh: int32 scalars; qb/kb: GLOBAL tile indices (int32, traced);
    q_col/k_row: [bq, 1] / [1, bk] global positions for the hash fallback.
    All three kernels tile scores congruently ([block_q, block_k], q rows x
    k cols), so (qb, kb) identifies the same cells everywhere.

    ``hw_rng=False`` forces the position-keyed hash even on real TPUs: the
    HW PRNG stream is keyed on TILE ids and tile-shaped draws, so it is
    only reproducible between kernels that tile identically — ring-CP pair
    calls (fit to s_blk, not s) must use the hash to keep the realized
    mask equal to the unsharded kernel's for every cp layout.
    """
    if hw_rng and HW_RNG and not _interpret():
        pltpu.prng_seed(seed, bh, qb, kb)
        bits = pltpu.prng_random_bits(shape)
        bits = jax.lax.bitcast_convert_type(bits, jnp.int32)
        threshold = jnp.int32(int(rate * (1 << 31)))
        keep = (bits & jnp.int32(0x7FFFFFFF)) >= threshold
        return keep.astype(jnp.float32) / (1.0 - rate)
    return dropout_keep_scale(seed, bh, q_col, k_row, rate)


def _mm_dtype(dtype):
    """MXU operand dtype: bf16 operands run the MXU at full rate (f32
    accumulation comes from preferred_element_type); any other input dtype
    computes in f32 so the f32 parity tests stay tight."""
    return jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32


def _score_mask(q_pos, k_pos, kvlen, causal: bool):
    """Bool mask for a score tile: causal triangle ∧ key inside kv_lens."""
    mask = k_pos < kvlen
    if causal:
        mask &= q_pos >= k_pos
    return mask


def fit_blocks(s: int, want_q: int, want_k: int):
    """Largest (block_q, block_k) <= the requested sizes with
    block_k | block_q | s — so sequence lengths that are NOT multiples of
    the default 512 (e.g. 768, 1920) shrink the tile instead of being
    demoted to the XLA fallback path. Returns (None, None) when no 8-row
    tile divides ``s``. Trace-time Python only."""
    want_q = min(want_q, s)
    want_k = min(want_k, s, want_q)  # block_k | block_q requires bk <= bq
    block_k = next(
        (bk for bk in range(want_k - want_k % 8, 7, -8) if s % bk == 0), None
    )
    if block_k is None:
        return None, None
    block_q = next(
        bq for bq in range(want_q - want_q % block_k, 0, -block_k)
        if s % bq == 0
    )  # always terminates: bq == block_k divides s
    return block_q, block_k


def _major_block(s: int, tile: int, want: int) -> int:
    """Largest multiple of ``tile`` that divides ``s`` and is <= want
    (but at least ``tile``): the resident-block row count."""
    n = s // tile
    t = min(n, max(want // tile, 1))
    while n % t:
        t -= 1
    return t * tile


def _last_major(i, block_q: int, major: int, causal: bool, n_major: int):
    """Index of the last K/V major block the i-th q block attends to."""
    if not causal:
        return n_major - 1
    return ((i + 1) * block_q - 1) // major


def _kv_index_map(block_q: int, major: int, causal: bool, n_major: int):
    """K/V major-block index for grid step (bh, i, jm): clamped at the causal
    diagonal so steps past it repeat the previous index (no DMA)."""

    def index_map(b, i, jm):
        return b, jnp.minimum(jm, _last_major(i, block_q, major, causal,
                                              n_major)), 0

    return index_map


def _global_ids(meta_ref, bh):
    """Resolve the LOCAL batch*head grid index to the GLOBAL batch*head id
    plus global q/k position offsets, from the SMEM ``meta`` array
    [b0, h0, h_local, h_total, q_off, k_off]. Under ``shard_map`` (TP/DP
    sharding, ring-CP block calls) these keep the dropout bit stream keyed
    on global coordinates — mesh-layout-invariant by construction. The
    unsharded identity meta [0, 0, h, h, 0, 0] reproduces the exact
    pre-meta bit stream (gbh == bh, offsets 0)."""
    h_loc = meta_ref[2]
    gbh = ((meta_ref[0] + bh // h_loc) * meta_ref[3]
           + meta_ref[1] + bh % h_loc)
    return gbh, meta_ref[4], meta_ref[5]


def _fwd_kernel(seed_ref, kvlens_ref, meta_ref, q_ref, k_ref, v_ref, o_ref,
                lse_ref, m_scr, l_scr, acc_scr, *, block_k: int, major: int,
                scale: float, dropout_rate: float, causal: bool,
                n_major: int, hw_rng: bool = True):
    """Grid step (bh, q-block i, K/V major block jm): online-softmax updates
    over the compute tiles inside the resident major block."""
    bq, d = q_ref.shape
    bh = pl.program_id(0)
    i = pl.program_id(1)
    jm = pl.program_id(2)
    last_jm = _last_major(i, bq, major, causal, n_major)
    tiles = major // block_k

    @pl.when(jm == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(jm <= last_jm)
    def _step():
        # bf16 inputs stay bf16 INTO the MXU (f32 accumulation via
        # preferred_element_type) — f32 operands would run the MXU at
        # quarter rate; f32 inputs keep the full-precision path (tests)
        mm_dt = _mm_dtype(q_ref.dtype)
        q = q_ref[:].astype(mm_dt)
        kvlen = kvlens_ref[bh]
        gbh, q_off, k_off = _global_ids(meta_ref, bh)
        # positions as a [bq, 1] column / [1, bk] row: masking and the
        # dropout hash broadcast them, keeping per-cell VPU work minimal;
        # GLOBAL positions (q_off/k_off are 0 unless sharded)
        q_col = q_off + i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)

        def body(t, carry, masked: bool):
            m, l, acc = carry
            k_blk = k_ref[pl.ds(t * block_k, block_k), :].astype(mm_dt)
            v_blk = v_ref[pl.ds(t * block_k, block_k), :].astype(mm_dt)
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [bq, block_k]; scale post-dot keeps it f32
            k_row = (k_off + jm * major + t * block_k
                     + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            if masked:
                s = jnp.where(_score_mask(q_col, k_row, kvlen, causal),
                              s, NEG_INF)

            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            if masked:
                # fully-masked rows: keep p exactly 0 (avoids exp(NEG-NEG)=1
                # garbage rows feeding dV through p in the backward kernels)
                p = jnp.where(s > NEG_INF / 2, p, 0.0)
            alpha = jnp.exp(m - m_new)
            # The softmax normalizer sums the *undropped* probabilities;
            # dropout scales only the value path (out = drop(softmax(s)) @ v).
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            if dropout_rate > 0.0:
                p = p * _tile_keep_scale(
                    seed_ref[0], gbh, q_off // bq + i,
                    k_off // block_k + jm * tiles + t, q_col, k_row,
                    (bq, block_k), dropout_rate, hw_rng,
                )
            acc_new = alpha * acc + jax.lax.dot_general(
                p.astype(mm_dt), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        # two-phase walk: tiles strictly inside the causal triangle AND
        # fully below kv_lens skip all mask work (the bulk of the VPU cost);
        # only diagonal-crossing / kv-cut tiles run the masked body.
        # kvlen and the causal diagonal live in GLOBAL positions; the local
        # tile walk subtracts the offsets (both 0 unless sharded).
        kv_rel = kvlen - k_off
        dq_off = q_off - k_off
        n_kv_full = jnp.clip((kv_rel - jm * major) // block_k, 0, tiles)
        n_kv_any = jnp.clip(
            (kv_rel - jm * major + block_k - 1) // block_k, 0, tiles
        )
        if causal:
            n_causal = jnp.clip((dq_off + (i + 1) * bq - jm * major)
                                // block_k, 0, tiles)
            n_causal_free = jnp.clip((dq_off + i * bq - jm * major + 1)
                                     // block_k, 0, tiles)
            n_inner = jnp.minimum(n_causal, n_kv_any)
            n_free = jnp.minimum(n_causal_free, n_kv_full)
        else:
            n_inner = n_kv_any
            n_free = n_kv_full
        n_free = jnp.minimum(n_free, n_inner)
        carry = (m_scr[:], l_scr[:], acc_scr[:])
        carry = jax.lax.fori_loop(
            0, n_free, functools.partial(body, masked=False), carry
        )
        m, l, acc = jax.lax.fori_loop(
            n_free, n_inner, functools.partial(body, masked=True), carry
        )
        m_scr[:] = m
        l_scr[:] = l
        acc_scr[:] = acc

    @pl.when(jm == last_jm)
    def _finalize():
        l = l_scr[:]
        l_safe = jnp.where(l > 0.0, l, 1.0)  # fully-masked rows emit zeros
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[:] = m_scr[:] + jnp.log(l_safe)  # [bq, 1] tile of (bh, s, 1)


def _bwd_dq_kernel(seed_ref, kvlens_ref, meta_ref, q_ref, k_ref, v_ref,
                   do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *,
                   block_k: int, major: int, scale: float,
                   dropout_rate: float, causal: bool, n_major: int,
                   hw_rng: bool = True):
    bq, d = q_ref.shape
    bh = pl.program_id(0)
    i = pl.program_id(1)
    jm = pl.program_id(2)
    last_jm = _last_major(i, bq, major, causal, n_major)
    tiles = major // block_k

    @pl.when(jm == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    @pl.when(jm <= last_jm)
    def _step():
        mm_dt = _mm_dtype(q_ref.dtype)
        q = q_ref[:].astype(mm_dt)
        do = do_ref[:].astype(mm_dt)
        lse = lse_ref[:]      # [bq, 1]
        delta = delta_ref[:]  # [bq, 1]
        kvlen = kvlens_ref[bh]
        gbh, q_off, k_off = _global_ids(meta_ref, bh)
        q_col = q_off + i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)

        def body(t, dq, masked: bool):
            k_blk = k_ref[pl.ds(t * block_k, block_k), :].astype(mm_dt)
            v_blk = v_ref[pl.ds(t * block_k, block_k), :].astype(mm_dt)
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            k_row = (k_off + jm * major + t * block_k
                     + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            if masked:
                mask = _score_mask(q_col, k_row, kvlen, causal)
                p = jnp.where(mask, jnp.exp(s - lse), 0.0)
            else:
                p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if dropout_rate > 0.0:
                # dP = (dO @ V^T) ∘ mask; delta already equals rowsum(P ∘ dP)
                # because delta = rowsum(dO ∘ O) and O = (P ∘ mask) @ V.
                dp = dp * _tile_keep_scale(
                    seed_ref[0], gbh, q_off // bq + i,
                    k_off // block_k + jm * tiles + t, q_col, k_row,
                    (bq, block_k), dropout_rate, hw_rng,
                )
            ds = p * (dp - delta)
            return dq + jax.lax.dot_general(
                ds.astype(mm_dt), k_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        kv_rel = kvlen - k_off
        dq_off = q_off - k_off
        n_kv_full = jnp.clip((kv_rel - jm * major) // block_k, 0, tiles)
        n_kv_any = jnp.clip(
            (kv_rel - jm * major + block_k - 1) // block_k, 0, tiles
        )
        if causal:
            n_causal = jnp.clip((dq_off + (i + 1) * bq - jm * major)
                                // block_k, 0, tiles)
            n_causal_free = jnp.clip((dq_off + i * bq - jm * major + 1)
                                     // block_k, 0, tiles)
            n_inner = jnp.minimum(n_causal, n_kv_any)
            n_free = jnp.minimum(n_causal_free, n_kv_full)
        else:
            n_inner = n_kv_any
            n_free = n_kv_full
        n_free = jnp.minimum(n_free, n_inner)
        dq = jax.lax.fori_loop(
            0, n_free, functools.partial(body, masked=False), dq_scr[:]
        )
        dq_scr[:] = jax.lax.fori_loop(
            n_free, n_inner, functools.partial(body, masked=True), dq
        )

    @pl.when(jm == last_jm)
    def _finalize():
        dq_ref[:] = (dq_scr[:] * scale).astype(dq_ref.dtype)


def _first_major(j, block_k: int, major: int, causal: bool):
    """Index of the first Q major block that sees the j-th k block."""
    if not causal:
        return 0
    return (j * block_k) // major


def _q_stream_index_map(block_k: int, major: int, causal: bool):
    """Q-side major-block index for dkv grid step (bh, j, im): clamped below
    at the causal diagonal so pre-diagonal steps repeat one index (no DMA)."""

    def index_map(b, j, im):
        return b, jnp.maximum(im, _first_major(j, block_k, major, causal)), 0

    return index_map


def _bwd_dkv_kernel(seed_ref, kvlens_ref, meta_ref, q_ref, k_ref, v_ref,
                    do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr,
                    dv_scr, *, block_q: int, major: int, scale: float,
                    dropout_rate: float, causal: bool, n_major: int,
                    hw_rng: bool = True):
    bk, d = k_ref.shape
    bh = pl.program_id(0)
    j = pl.program_id(1)
    im = pl.program_id(2)
    first_im = _first_major(j, bk, major, causal)
    tiles = major // block_q

    @pl.when(im == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    kvlen = kvlens_ref[bh]
    gbh, q_off, k_off = _global_ids(meta_ref, bh)

    # skip entirely when this k block sits fully past the kv cut: every
    # score is masked, dk/dv stay zero (the init/finalize still run) —
    # saves the all-tiles masked walk for heavily right-padded rows
    @pl.when((im >= first_im) & (k_off + j * bk < kvlen))
    def _step():
        mm_dt = _mm_dtype(k_ref.dtype)
        k = k_ref[:].astype(mm_dt)
        v = v_ref[:].astype(mm_dt)
        k_row = k_off + j * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)

        def body(t, carry, masked: bool):
            dk, dv = carry
            q_blk = q_ref[pl.ds(t * block_q, block_q), :].astype(mm_dt)
            do_blk = do_ref[pl.ds(t * block_q, block_q), :].astype(mm_dt)
            lse = lse_ref[pl.ds(t * block_q, block_q), :]      # [block_q, 1]
            delta = delta_ref[pl.ds(t * block_q, block_q), :]  # [block_q, 1]
            s = jax.lax.dot_general(
                q_blk, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            q_col = (q_off + im * major + t * block_q
                     + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
            if masked:
                mask = _score_mask(q_col, k_row, kvlen, causal)
                p = jnp.where(mask, jnp.exp(s - lse), 0.0)
            else:
                p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(
                do_blk, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if dropout_rate > 0.0:
                drop = _tile_keep_scale(
                    seed_ref[0], gbh, q_off // block_q + im * tiles + t,
                    k_off // bk + j, q_col, k_row,
                    (block_q, bk), dropout_rate, hw_rng,
                )
                p_v = p * drop  # dropped probabilities feed dV
                dp = dp * drop
            else:
                p_v = p
            dv_new = dv + jax.lax.dot_general(
                p_v.astype(mm_dt), do_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta)
            dk_new = dk + jax.lax.dot_general(
                ds.astype(mm_dt), q_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return dk_new, dv_new

        dk_off = k_off - q_off
        if causal:
            # first q tile inside this major block at/after the diagonal
            t0 = jnp.clip((dk_off + j * bk - im * major) // block_q,
                          0, tiles)
            # first q tile fully past the diagonal (min q >= max k): mask-free
            t_free_c = jnp.clip(
                (dk_off + (j + 1) * bk - 1 - im * major + block_q - 1)
                // block_q, 0, tiles,
            )
        else:
            t0 = jnp.int32(0)
            t_free_c = jnp.int32(0)
        # a kv cut inside this k block masks EVERY q tile (column mask)
        kv_full = k_off + (j + 1) * bk <= kvlen
        t_free = jnp.where(kv_full, jnp.maximum(t_free_c, t0),
                           jnp.int32(tiles))
        carry = (dk_scr[:], dv_scr[:])
        carry = jax.lax.fori_loop(
            t0, jnp.minimum(t_free, tiles),
            functools.partial(body, masked=True), carry,
        )
        dk, dv = jax.lax.fori_loop(
            t_free, tiles, functools.partial(body, masked=False), carry
        )
        dk_scr[:] = dk
        dv_scr[:] = dv

    @pl.when(im == n_major - 1)
    def _finalize():
        # q was loaded UNSCALED (bf16 MXU path), so the chain rule's scale
        # factor lands here: dL/dk = scale * ds^T @ q
        dk_ref[:] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _to_bh(x):
    """[b, s, h, d] -> [b*h, s, d]"""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bh(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _seed_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _fwd_call(seed, kvlens, meta, q3, k3, v3, block_q, block_k, scale,
              dropout_rate, causal, hw_rng=True):
    bh, s, d = q3.shape
    major = _major_block(s, block_k, DEFAULT_BLOCK_MAJOR)
    n_major = s // major
    grid = (bh, s // block_q, n_major)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, major=major, scale=scale,
        dropout_rate=dropout_rate, causal=causal, n_major=n_major,
        hw_rng=hw_rng,
    )
    kv_map = _kv_index_map(block_q, major, causal, n_major)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _seed_spec(),
            _seed_spec(),
            _seed_spec(),
            pl.BlockSpec((None, block_q, d), lambda b, i, jm: (b, i, 0)),
            pl.BlockSpec((None, major, d), kv_map),
            pl.BlockSpec((None, major, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, jm: (b, i, 0)),
            # trailing singleton dim: Mosaic requires the last block dim to
            # divide 128 or equal the array dim — (block_q, 1) satisfies it
            pl.BlockSpec((None, block_q, 1), lambda b, i, jm: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(seed, kvlens, meta, q3, k3, v3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _flash(q, k, v, seed, kvlens, meta, block_q, block_k, dropout_rate,
           causal):
    out, _ = _flash_fwd(q, k, v, seed, kvlens, meta, block_q, block_k,
                        dropout_rate, causal)
    return out


def _flash_fwd(q, k, v, seed, kvlens, meta, block_q, block_k, dropout_rate,
               causal):
    b, s, h, d = q.shape
    scale = 1.0 / (d**0.5)
    q3, k3, v3 = _to_bh(q), _to_bh(k), _to_bh(v)
    o3, lse = _fwd_call(
        seed, kvlens, meta, q3, k3, v3, block_q, block_k, scale, dropout_rate,
        causal
    )
    return _from_bh(o3, b, h), (q3, k3, v3, o3, lse, seed, kvlens, meta, b, h)


def _dq_call(seed, kvlens, meta, q3, k3, v3, do3, lse, delta, block_q,
             block_k, scale, dropout_rate, causal, hw_rng=True):
    """dq kernel dispatch ([bh, s, d] operands; lse/delta [bh, s, 1])."""
    bh, s, d = q3.shape
    kv_major = _major_block(s, block_k, DEFAULT_BLOCK_MAJOR)
    n_kv_major = s // kv_major
    kv_map = _kv_index_map(block_q, kv_major, causal, n_kv_major)
    return pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, major=kv_major, scale=scale,
            dropout_rate=dropout_rate, causal=causal, n_major=n_kv_major,
            hw_rng=hw_rng,
        ),
        grid=(bh, s // block_q, n_kv_major),
        in_specs=[
            _seed_spec(),
            _seed_spec(),
            _seed_spec(),
            pl.BlockSpec((None, block_q, d), lambda b_, i, jm: (b_, i, 0)),
            pl.BlockSpec((None, kv_major, d), kv_map),
            pl.BlockSpec((None, kv_major, d), kv_map),
            pl.BlockSpec((None, block_q, d), lambda b_, i, jm: (b_, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b_, i, jm: (b_, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b_, i, jm: (b_, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda b_, i, jm: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(seed, kvlens, meta, q3, k3, v3, do3, lse, delta)


def _dkv_call(seed, kvlens, meta, q3, k3, v3, do3, lse, delta, block_q,
              block_k, scale, dropout_rate, causal, hw_rng=True):
    """dk/dv kernel dispatch ([bh, s, d] operands; lse/delta [bh, s, 1])."""
    bh, s, d = q3.shape
    q_major = _major_block(s, block_q, DEFAULT_BLOCK_MAJOR)
    n_q_major = s // q_major
    q_map = _q_stream_index_map(block_k, q_major, causal)
    return pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, major=q_major, scale=scale,
            dropout_rate=dropout_rate, causal=causal, n_major=n_q_major,
            hw_rng=hw_rng,
        ),
        grid=(bh, s // block_k, n_q_major),
        in_specs=[
            _seed_spec(),
            _seed_spec(),
            _seed_spec(),
            pl.BlockSpec((None, q_major, d), q_map),
            pl.BlockSpec((None, block_k, d), lambda b_, j, im: (b_, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b_, j, im: (b_, j, 0)),
            pl.BlockSpec((None, q_major, d), q_map),
            pl.BlockSpec((None, q_major, 1), q_map),
            pl.BlockSpec((None, q_major, 1), q_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b_, j, im: (b_, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b_, j, im: (b_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(seed, kvlens, meta, q3, k3, v3, do3, lse, delta)


def _flash_bwd(block_q, block_k, dropout_rate, causal, res, g):
    q3, k3, v3, o3, lse, seed, kvlens, meta, b, h = res
    bh, s, d = q3.shape
    scale = 1.0 / (d**0.5)
    do3 = _to_bh(g)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [bh, s, 1]
    dq3 = _dq_call(seed, kvlens, meta, q3, k3, v3, do3, lse, delta,
                   block_q, block_k, scale, dropout_rate, causal)
    dk3, dv3 = _dkv_call(seed, kvlens, meta, q3, k3, v3, do3, lse, delta,
                         block_q, block_k, scale, dropout_rate, causal)

    dq = _from_bh(dq3, b, h)
    dk = _from_bh(dk3, b, h)
    dv = _from_bh(dv3, b, h)
    # seed/kvlens/meta are integer-dtype: their cotangent type is float0
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    dkvlens = np.zeros(kvlens.shape, dtype=jax.dtypes.float0)
    dmeta = np.zeros(meta.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dseed, dkvlens, dmeta


_flash.defvjp(_flash_fwd, _flash_bwd)


# ------------------------------------------------- ring-CP building blocks
# Per-(q-block, kv-block) kernel entry points for ring attention
# (parallel/context_parallel.py): [b, s, h, d] operands, explicit global
# position offsets via ``meta``, and the log-sum-exp exposed so hops can be
# merged in (out, lse) space. The ring owns its own custom VJP (re-rotating
# KV), so these are raw primal/cotangent dispatches, not custom_vjp'd.
#
# Offset rule: ``causal=True`` requires meta's q_off == k_off (the DMA
# index-map diagonal clamp assumes an aligned diagonal — exactly the ring's
# same-block-id case); cross-block pairs are fully ordered and call with
# causal=False.

def _ring_blocks(s: int):
    bq, bk = fit_blocks(s, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    if bq is None:
        raise ValueError(f"ring block seq {s} not tileable (multiple of 8)")
    return bq, bk


def _lse_to_bsh(lse3, b, h):
    """[b*h, s, 1] f32 -> [b, s, h]"""
    bh, s, _ = lse3.shape
    return lse3[..., 0].reshape(b, h, s).transpose(0, 2, 1)


def _lse_from_bsh(lse, b, h):
    """[b, s, h] f32 -> [b*h, s, 1]"""
    s = lse.shape[1]
    return lse.transpose(0, 2, 1).reshape(b * h, s, 1)


def block_fwd_lse(q, k, v, seed, meta, *, causal, dropout_rate, kv_len):
    """Flash forward on one (q-block, kv-block) pair.

    Returns (out [b, s, h, d], lse [b, s, h] f32). ``kv_len`` is the GLOBAL
    total key length (keys are masked at k_pos >= kv_len; pass the full
    sequence length when there is no padding)."""
    b, s, h, d = q.shape
    block_q, block_k = _ring_blocks(s)
    kvlens = jnp.full((b * h,), kv_len, jnp.int32)
    o3, lse3 = _fwd_call(
        seed, kvlens, meta, _to_bh(q), _to_bh(k), _to_bh(v), block_q,
        block_k, 1.0 / (d**0.5), dropout_rate, causal, hw_rng=False,
    )
    return _from_bh(o3, b, h), _lse_to_bsh(lse3, b, h)


def block_dq(q, k, v, do, lse, delta, seed, meta, *, causal, dropout_rate,
             kv_len):
    """dq of one pair given the MERGED lse/delta ([b, s, h] f32) of the q
    rows — the flash-attention identity lets each hop's dq be computed
    against the global softmax statistics."""
    b, s, h, d = q.shape
    block_q, block_k = _ring_blocks(s)
    kvlens = jnp.full((b * h,), kv_len, jnp.int32)
    dq3 = _dq_call(
        seed, kvlens, meta, _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(do),
        _lse_from_bsh(lse, b, h), _lse_from_bsh(delta, b, h), block_q,
        block_k, 1.0 / (d**0.5), dropout_rate, causal, hw_rng=False,
    )
    return _from_bh(dq3, b, h)


def block_dkv(q, k, v, do, lse, delta, seed, meta, *, causal, dropout_rate,
              kv_len):
    """(dk, dv) of one pair given merged lse/delta of the q rows."""
    b, s, h, d = q.shape
    block_q, block_k = _ring_blocks(s)
    kvlens = jnp.full((b * h,), kv_len, jnp.int32)
    dk3, dv3 = _dkv_call(
        seed, kvlens, meta, _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(do),
        _lse_from_bsh(lse, b, h), _lse_from_bsh(delta, b, h), block_q,
        block_k, 1.0 / (d**0.5), dropout_rate, causal, hw_rng=False,
    )
    return _from_bh(dk3, b, h), _from_bh(dv3, b, h)


def _identity_meta(h: int) -> jax.Array:
    """Meta for an unsharded call: global ids == local ids, offsets 0."""
    return jnp.asarray([0, 0, h, h, 0, 0], jnp.int32)


def _shardable_mesh(q, h: int):
    """The ambient mesh to shard_map the kernel over, or None.

    Engaged only when a mesh with a non-trivial dp/fsdp/mp extent is active
    and the batch/head dims divide it. Returns None inside a vmap trace
    (the GSPMD pipeline applies stages under nn.vmap — a nested shard_map
    there would conflict with the stage sharding; callers on the pp path
    pass mesh_shard=False at the ops/attention.py level as the primary
    guard, this tracer check is the backstop for direct vmapped calls)."""
    try:  # private path: degrade to no-backstop if a jax refactor moves it
        from jax._src.interpreters import batching as _batching

        if isinstance(q, _batching.BatchTracer):
            return None
    except ImportError:  # pragma: no cover
        pass
    from fleetx_tpu.parallel.mesh import ambient_mesh

    mesh = ambient_mesh()
    if mesh is None:
        return None
    n_data, n_mp = _mesh_extents(mesh)
    if n_data * n_mp <= 1:
        return None
    if q.shape[0] % n_data or h % n_mp:
        return None
    return mesh


def _mesh_extents(mesh):
    """(data world, mp world) — single source for the wrapper's degrees."""
    sizes = dict(mesh.shape)
    return sizes.get("dp", 1) * sizes.get("fsdp", 1), sizes.get("mp", 1)


def _sharded_flash(mesh, q, k, v, seed, kv_lens, block_q, block_k,
                   dropout_rate, causal):
    """shard_map the kernel over (batch -> dp/fsdp, heads -> mp).

    Without this, GSPMD treats the Pallas call as an opaque custom call and
    replicates q/k/v — i.e. an all-gather of the TP-sharded heads right
    around the flagship kernel (VERDICT r4 weak #3). The manual region keeps
    heads sharded exactly like the reference's column-parallel qkv implies
    (hybrid_model.py:131-174: heads-sharded core_attn). Dropout bits stay
    identical to the unsharded call because the kernel hashes/seeds on
    GLOBAL (batch*head, position) ids via ``meta``."""
    from jax.sharding import PartitionSpec as P

    sizes = dict(mesh.shape)
    b, s, h, _ = q.shape
    data_axes = tuple(a for a in ("dp", "fsdp") if sizes.get(a, 1) > 1)
    head_axis = "mp" if sizes.get("mp", 1) > 1 else None
    n_data, n_mp = _mesh_extents(mesh)
    b_loc, h_loc = b // n_data, h // n_mp

    def body(q, k, v, seed, kvl):
        d_idx = jnp.int32(0)
        for a in data_axes:
            d_idx = d_idx * sizes[a] + jax.lax.axis_index(a)
        h_idx = jax.lax.axis_index(head_axis) if head_axis else jnp.int32(0)
        meta = jnp.stack([
            d_idx * b_loc,               # global batch offset
            h_idx * h_loc,               # global head offset
            jnp.int32(h_loc), jnp.int32(h),
            jnp.int32(0), jnp.int32(0),  # seq not sharded here
        ])
        kvlens_bh = jnp.repeat(kvl, h_loc)
        return _flash(q, k, v, seed, kvlens_bh, meta, block_q, block_k,
                      dropout_rate, causal)

    spec = P(data_axes or None, None, head_axis, None)
    from fleetx_tpu.parallel.mesh import shard_map

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(None), P(data_axes or None)),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, seed, kv_lens)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    *,
    causal: bool = True,
    kv_lens: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    mesh_shard: bool = True,
) -> jax.Array:
    """Flash attention, [b, s, h, d] layout. Sequence length must be a
    multiple of the block sizes (callers fall back to the XLA path
    otherwise — fleetx_tpu/ops/attention.py). ``kv_lens`` [b] int32 masks
    right-padded keys (position k valid iff k < kv_lens[b]); ``causal=False``
    gives bidirectional (encoder) attention. ``dropout_rate > 0`` requires a
    ``dropout_rng`` key; the mask is generated inside the kernel.

    When a device mesh with dp/fsdp/mp extents is ambient (Trainer's
    ``use_mesh``), the kernel is wrapped in ``shard_map`` over
    (batch -> data axes, heads -> mp) so GSPMD shards the custom call
    instead of replicating it; ``mesh_shard=False`` opts out (the pp>1
    stage-vmap path must — see fleetx_tpu/ops/attention.py)."""
    b, s, h, _ = q.shape
    want_q = block_q
    block_q, block_k = fit_blocks(s, block_q, block_k)
    if block_q is None:
        raise ValueError(f"seq {s} not tileable (must be a multiple of 8)")
    if block_q < min(128, want_q) and block_q != s:
        # the model path pre-screens with _tileable (ops/attention.py), but
        # direct callers can land on sequences whose largest divisor tile is
        # tiny — a silent 10x+ perf cliff vs the requested blocks
        import warnings

        warnings.warn(
            f"flash_attention: seq {s} only admits {block_q}x{block_k} "
            f"tiles (requested {want_q}); per-grid-step overhead will "
            "dominate — pad the sequence to a multiple of 128 or use the "
            "XLA path",
            stacklevel=2,
        )
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        seed = jax.random.bits(dropout_rng, (1,), "uint32").astype(jnp.int32)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    mesh = _shardable_mesh(q, h) if mesh_shard else None
    if mesh is not None:
        kv_lens_b = (jnp.full((b,), s, jnp.int32) if kv_lens is None
                     else kv_lens.astype(jnp.int32))
        return _sharded_flash(mesh, q, k, v, seed, kv_lens_b, block_q,
                              block_k, float(dropout_rate), bool(causal))
    if kv_lens is None:
        kvlens_bh = jnp.full((b * h,), s, jnp.int32)
    else:
        kvlens_bh = jnp.repeat(kv_lens.astype(jnp.int32), h)  # [b*h]
    return _flash(q, k, v, seed, kvlens_bh, _identity_meta(h), block_q,
                  block_k, float(dropout_rate), bool(causal))
