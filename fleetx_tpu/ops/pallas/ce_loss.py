"""Fused LM-head + cross-entropy — Pallas TPU kernels with custom VJP.

The reference computes logits with a (vocab-parallel) matmul and feeds
them to a softmax-CE criterion (/root/reference/ppfleetx/models/
language_model/gpt/dygraph/single_model.py:660-736 ``GPTForPretraining``
+ ``GPTPretrainingCriterion``), materializing [tokens, vocab] twice
(logits + softmax grad). At GPT vocab 50304 and bench shapes
(8x1024 tokens) that is ~1.6 GB of f32 activations each way — the
largest tensor in the model. This kernel streams vocab blocks through
VMEM with an online logsumexp, so the full logits matrix never reaches
HBM:

- forward: grid (token-block i, vocab-block j), j innermost sequential;
  one [bt, H] hidden block stays resident while [bv, H] embedding blocks
  stream; scratch carries (running max, running sumexp, label logit);
  emits per-token loss and the logsumexp.
- backward: dlogits = softmax(s) - onehot(label) is REcomputed blockwise
  from the saved logsumexp (the flash-attention trick applied to CE):
  the dh kernel accumulates dlogits @ W over vocab blocks; the dW kernel
  accumulates dlogits^T @ h over token blocks. Two extra matmul passes
  (~9% step FLOPs at 345M) buy back the logits' HBM round-trips and the
  1.6 GB live-activation peak — the final staged lever in
  docs/PERFORMANCE.md.

Requires the (per-shard) vocab to admit a lane-aligned block — a
128-multiple <= 512 dividing it, or the 64-lane fallback (see
``fit_vocab_block``); callers fall back to the XLA path otherwise.
Tokens dim must be a multiple of 8. Under an mp>1 mesh the
vocab-parallel form shards the embedding and combines per-shard
(logsumexp, label-logit) stats outside the shard_map region.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_linear_ce", "fit_vocab_block"]

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _params_2d():
    # j (vocab / token stream) is the innermost scratch-carrying axis
    from fleetx_tpu.ops.pallas.flash_attention import CompilerParams

    return CompilerParams(dimension_semantics=("parallel", "arbitrary"))


def fit_vocab_block(v: int, want: int = 512):
    """Largest lane-aligned block dividing ``v`` and <= want, or None (the
    caller then uses the XLA path). Preference: a multiple of 128 (full
    lanes); fallback: 64 (Mosaic also accepts last block dims DIVIDING
    128, and 64 keeps half the lanes — e.g. the GPT vocab 50304 sharded
    mp2 is 25152 = 64*393, 128-unaligned). Below 64 the lane waste makes
    the kernel pointless, so smaller divisors demote instead."""
    for bv in range(want - want % 128, 127, -128):
        if v % bv == 0:
            return bv
    if v % 64 == 0:
        return 64
    return None


def _fit_token_block(n: int, want: int = 256):
    for bt in range(want - want % 8, 7, -8):
        if n % bt == 0:
            return bt
    return None


def _mm_dt(dtype):
    return jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32


def _fwd_kernel(labels_ref, h_ref, w_ref, loss_ref, lse_ref, m_scr, l_scr,
                lab_scr, *, block_v: int, n_v: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        lab_scr[:] = jnp.zeros(lab_scr.shape, jnp.float32)

    mm = _mm_dt(h_ref.dtype)
    h = h_ref[:].astype(mm)
    w = w_ref[:].astype(mm)
    s = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )  # [bt, bv]
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (1, block_v), 1)
    m = m_scr[:]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    l_scr[:] = l_scr[:] * jnp.exp(m - m_new) + jnp.sum(
        jnp.exp(s - m_new), axis=-1, keepdims=True)
    m_scr[:] = m_new
    hit = labels_ref[:] == col  # [bt, 1] == [1, bv] -> [bt, bv]
    lab_scr[:] = lab_scr[:] + jnp.sum(
        jnp.where(hit, s, 0.0), axis=-1, keepdims=True)

    @pl.when(j == n_v - 1)
    def _fin():
        lse = m_scr[:] + jnp.log(l_scr[:])
        lse_ref[:] = lse
        loss_ref[:] = lse - lab_scr[:]


def _dh_kernel(labels_ref, a_ref, b_ref, lse_ref, h_ref, w_ref, dh_ref,
               dh_scr, *, block_v: int, n_v: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dh_scr[:] = jnp.zeros(dh_scr.shape, jnp.float32)

    mm = _mm_dt(h_ref.dtype)
    h = h_ref[:].astype(mm)
    w = w_ref[:].astype(mm)
    s = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )
    p = jnp.exp(s - lse_ref[:])  # softmax via saved logsumexp
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (1, block_v), 1)
    # generalized cotangent dl = a*softmax + b*onehot: the plain CE
    # backward is (a, b) = (g, -g); the vocab-parallel stats primitive
    # feeds the cotangents of (lse_loc, lab_loc) directly
    dl = (a_ref[:] * p
          + b_ref[:] * jnp.where(labels_ref[:] == col, 1.0, 0.0))
    dh_scr[:] = dh_scr[:] + jax.lax.dot_general(
        dl.astype(mm), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_v - 1)
    def _fin():
        dh_ref[:] = dh_scr[:].astype(dh_ref.dtype)


def _dw_kernel(labels_ref, a_ref, b_ref, lse_ref, h_ref, w_ref, dw_ref,
               dw_scr, *, block_t: int, n_t: int, block_v: int):
    j = pl.program_id(0)  # vocab block (parallel)
    i = pl.program_id(1)  # token stream (sequential)

    @pl.when(i == 0)
    def _init():
        dw_scr[:] = jnp.zeros(dw_scr.shape, jnp.float32)

    mm = _mm_dt(h_ref.dtype)
    h = h_ref[:].astype(mm)
    w = w_ref[:].astype(mm)
    s = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )  # [bt, bv]
    p = jnp.exp(s - lse_ref[:])
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (1, block_v), 1)
    dl = (a_ref[:] * p
          + b_ref[:] * jnp.where(labels_ref[:] == col, 1.0, 0.0))
    dw_scr[:] = dw_scr[:] + jax.lax.dot_general(
        dl.astype(mm), h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bv, H]

    @pl.when(i == n_t - 1)
    def _fin():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ce(h, w, labels, block_t, block_v):
    out, _ = _fused_ce_fwd(h, w, labels, block_t, block_v)
    return out


def _fused_ce_fwd(h, w, labels, block_t, block_v):
    n, d = h.shape
    v = w.shape[0]
    n_t, n_v = n // block_t, v // block_v
    lab2 = labels.astype(jnp.int32)[:, None]  # [n, 1]
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v, n_v=n_v),
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
        ],
        compiler_params=_params_2d(),
        interpret=_interpret(),
    )(lab2, h, w)
    return loss[:, 0], (h, w, lab2, lse)


def _fused_ce_bwd(block_t, block_v, res, g):
    h, w, lab2, lse = res
    n, d = h.shape
    v = w.shape[0]
    n_t, n_v = n // block_t, v // block_v
    g2 = g.astype(jnp.float32)[:, None]  # [n, 1]
    dh = _dh_call(lab2, g2, -g2, lse, h, w, block_t, block_v)
    dw = _dw_call(lab2, g2, -g2, lse, h, w, block_t, block_v)
    dlabels = np.zeros(lab2.shape[:1], dtype=jax.dtypes.float0)
    return dh, dw, dlabels


def _dh_call(lab2, a2, b2, lse, h, w, block_t, block_v):
    n, d = h.shape
    n_t, n_v = n // block_t, w.shape[0] // block_v
    return pl.pallas_call(
        functools.partial(_dh_kernel, block_v=block_v, n_v=n_v),
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), h.dtype),
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
        compiler_params=_params_2d(),
        interpret=_interpret(),
    )(lab2, a2, b2, lse, h, w)


def _dw_call(lab2, a2, b2, lse, h, w, block_t, block_v):
    n, d = h.shape
    v = w.shape[0]
    n_t, n_v = n // block_t, v // block_v
    return pl.pallas_call(
        functools.partial(_dw_kernel, block_t=block_t, n_t=n_t,
                          block_v=block_v),
        grid=(n_v, n_t),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_t, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((v, d), w.dtype),
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        compiler_params=_params_2d(),
        interpret=_interpret(),
    )(lab2, a2, b2, lse, h, w)


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


# ------------------------------------------------ vocab-parallel (TP) form
# The reference's vocab-parallel LM head + ParallelCrossEntropy
# (hybrid_model.py:49-71, 857-904) as a kernel: each mp shard runs the
# SAME Pallas kernels over its vocab shard and returns per-shard
# (logsumexp, label-logit) stats on a MENTIONED mp output axis; the
# cross-shard combine (exact logsumexp + sum) happens OUTSIDE the
# shard_map in plain jnp, where autodiff is trivially exact. (Replicated
# outputs under check_vma=False transpose with an ambiguous scale — the
# stats formulation sidesteps that entirely.) The stats primitive's VJP
# uses the generalized kernel cotangent dl = a*softmax_local + b*onehot.

def _local_labels(labels, v_loc: int, mp_axis: str):
    """Global label ids -> this shard's local ids; off-shard -> -1 (matches
    no column, so the local label-logit stays 0 and the cross-shard sum
    recovers exactly the owning shard's value)."""
    shard = jax.lax.axis_index(mp_axis)
    l_loc = labels.astype(jnp.int32) - shard * v_loc
    return jnp.where((l_loc >= 0) & (l_loc < v_loc), l_loc, -1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _vp_stats(h, w_shard, l_loc, block_t, block_v):
    out, _ = _vp_stats_fwd(h, w_shard, l_loc, block_t, block_v)
    return out


def _vp_stats_fwd(h, w_shard, l_loc, block_t, block_v):
    loss_loc, (_, _, lab2, lse) = _fused_ce_fwd(
        h, w_shard, l_loc, block_t, block_v)
    lse1 = lse[:, 0]
    lab1 = lse1 - loss_loc  # 0 when the label lives on another shard
    return (lse1, lab1), (h, w_shard, lab2, lse)


def _vp_stats_bwd(block_t, block_v, res, cts):
    h, w_shard, lab2, lse = res
    ca, cb = cts  # cotangents of (lse_loc, lab_loc)
    a2 = ca.astype(jnp.float32)[:, None]
    b2 = cb.astype(jnp.float32)[:, None]
    dh = _dh_call(lab2, a2, b2, lse, h, w_shard, block_t, block_v)
    dw = _dw_call(lab2, a2, b2, lse, h, w_shard, block_t, block_v)
    dlabels = np.zeros(lab2.shape[:1], dtype=jax.dtypes.float0)
    return dh, dw, dlabels


_vp_stats.defvjp(_vp_stats_fwd, _vp_stats_bwd)


def fused_linear_ce(hidden: jax.Array, emb: jax.Array,
                    labels: jax.Array) -> jax.Array:
    """Per-token CE loss of ``logits = hidden @ emb^T`` without ever
    materializing the logits. hidden [n, d] (model dtype), emb [v, d]
    (same dtype), labels [n] int — returns [n] f32 token losses
    (callers apply loss_mask / normalization).

    Under an ambient mesh the call shard_maps over the token dim
    (dp/fsdp) and, when mp > 1, over the VOCAB dim of the embedding too
    (vocab-parallel: per-shard stats combined outside the region).
    Raises ValueError when no lane-aligned blocks fit — callers gate
    with :func:`fit_vocab_block` on the PER-SHARD vocab (v // mp) and
    fall back to the XLA logits path."""
    n, d = hidden.shape
    v = emb.shape[0]
    block_v = fit_vocab_block(v)
    if block_v is None:
        raise ValueError(
            f"fused_linear_ce: vocab {v} admits no lane-aligned block "
            "(need a 128-multiple <= 512 dividing it, or 64 | v)"
        )

    from fleetx_tpu.parallel.mesh import ambient_mesh
    from fleetx_tpu.parallel.mesh import shard_map as _shard_map

    mesh = ambient_mesh()
    n_data, n_mp = 1, 1
    if mesh is not None:
        sizes = dict(mesh.shape)
        n_data = sizes.get("dp", 1) * sizes.get("fsdp", 1)
        n_mp = sizes.get("mp", 1)
        if n % n_data or (n_mp > 1 and v % n_mp):
            mesh = None  # indivisible: run unsharded (GSPMD replicates)
    if mesh is not None and n_data * n_mp > 1:
        from jax.sharding import PartitionSpec as P

        n_local = n // n_data
        block_t = _fit_token_block(n_local)
        if block_t is None:
            raise ValueError(f"fused_linear_ce: 8 must divide {n_local}")
        data_axes = tuple(a for a in ("dp", "fsdp")
                          if dict(mesh.shape).get(a, 1) > 1)
        if n_mp > 1:
            # vocab-parallel: embedding sharded over mp; per-shard stats
            # come back on a MENTIONED mp axis and combine outside (see
            # the vocab-parallel section above)
            v_loc = v // n_mp
            block_v_loc = fit_vocab_block(v_loc)
            if block_v_loc is None:
                raise ValueError(
                    f"fused_linear_ce: vocab shard {v_loc} admits no "
                    "lane-aligned block"
                )

            def body(h_, w_, l_):
                lse1, lab1 = _vp_stats(
                    h_, w_, _local_labels(l_, v_loc, "mp"),
                    block_t, block_v_loc)
                return lse1[None, :], lab1[None, :]

            fn = _shard_map(
                body,
                mesh=mesh,
                in_specs=(P(data_axes, None), P("mp", None), P(data_axes)),
                out_specs=(P("mp", data_axes), P("mp", data_axes)),
                check_vma=False,
            )
            lse_stack, lab_stack = fn(hidden, emb, labels)  # [mp, n]
            return (jax.scipy.special.logsumexp(lse_stack, axis=0)
                    - lab_stack.sum(axis=0))
        fn = _shard_map(
            # custom_vjp statics must stay positional
            lambda h_, w_, l_: _fused_ce(h_, w_, l_, block_t, block_v),
            mesh=mesh,
            in_specs=(P(data_axes, None), P(None, None), P(data_axes)),
            out_specs=P(data_axes),
            check_vma=False,
        )
        return fn(hidden, emb, labels)
    block_t = _fit_token_block(n)
    if block_t is None:
        raise ValueError(f"fused_linear_ce: 8 must divide tokens {n}")
    return _fused_ce(hidden, emb, labels, block_t, block_v)
