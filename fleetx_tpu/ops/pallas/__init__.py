"""Pallas TPU kernels (flash attention)."""
