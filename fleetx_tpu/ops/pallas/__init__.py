"""Pallas TPU kernels (flash attention, flash decode, fused CE loss)."""
