"""Counter-hash dropout: the TPU-cheap replacement for per-element threefry.

``flax.linen.Dropout`` draws its keep mask with ``jax.random.bernoulli``,
which on TPU lowers to a threefry2x32 keystream — ~100 VPU ops per pair of
random words. For the GPT hidden dropouts (2 per layer on [b, s, h]
activations, reference single_model.py:291,451 dropout1/dropout2) that RNG
was measured at ~12% of the 345M train step on v5e (round-4 A/B:
19,907 tok/s with hidden dropout off vs 18,112 on, BENCH_SESSION_r04).

``HashDropout`` keeps the same contract — deterministic given the
``'dropout'`` PRNG key, scale-by-1/(1-rate), zero where dropped — but
derives the per-element keep decision from the lowbias32 integer hash the
flash-attention kernel already uses for attention dropout
(fleetx_tpu/ops/pallas/flash_attention.py::dropout_keep_scale): ONE
threefry call per module call folds the key into an int32 seed, then each
element costs ~13 int32 VPU ops. The hash path is pure jnp, so it runs
identically on CPU tests and TPU, and autodiff flows through the multiply
(the mask itself is an integer computation with no gradient path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from fleetx_tpu.ops.pallas.flash_attention import dropout_keep_scale

__all__ = ["HashDropout", "dropout_layer"]


def dropout_layer(rate: float, name: str, fast: bool = True) -> nn.Module:
    """The one place models pick their hidden-dropout implementation:
    hash-based by default; ``fast=False`` (the per-family ``fast_dropout``
    config field) restores flax's threefry ``nn.Dropout`` as a rollback."""
    if fast:
        return HashDropout(rate, name=name)
    return nn.Dropout(rate, name=name)


class HashDropout(nn.Module):
    """Drop-in replacement for ``nn.Dropout`` (broadcast_dims unsupported).

    rate: drop probability. rng_collection: PRNG collection name, default
    ``'dropout'`` — same key => same mask, so trainers that derive
    per-data-rank dropout keys (parallel/env.py) keep mp-invariance.
    """

    rate: float
    rng_collection: str = "dropout"

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        if deterministic or self.rate == 0.0:
            return x
        if self.rate >= 1.0:
            return jnp.zeros_like(x)
        rng = self.make_rng(self.rng_collection)
        # one threefry draw per call (not per element): fold the key to the
        # int32 counter-hash seed
        seed = jax.random.bits(rng, (), "uint32").astype(jnp.int32)
        # element index as the hash counter; int32 covers activations up to
        # 2^31 elements (a [32, 2048, 12288] GPT-175B microbatch is 8e8)
        if x.size >= (1 << 31):
            raise ValueError(
                f"HashDropout supports < 2^31 elements per call; got shape "
                f"{x.shape} ({x.size}). Split the activation or use "
                f"fast_dropout=False."
            )
        idx = jax.lax.iota(jnp.int32, x.size).reshape(x.shape)
        scale = dropout_keep_scale(seed, jnp.int32(0), idx, jnp.int32(0),
                                   self.rate)
        return x * scale.astype(x.dtype)
