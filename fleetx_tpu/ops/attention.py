"""Core attention ops.

Replaces the reference's ``core_attn`` + CUDA ``softmax_mask_fuse_upper_
triangle`` (/root/reference/ppfleetx/models/language_model/gpt/dygraph/
single_model.py:216-240): on TPU the causal-masked softmax is either fused by
XLA from this straight-line jnp implementation or dispatched to the Pallas
flash-attention kernel (fleetx_tpu/ops/pallas/flash_attention.py) which never
materializes the [b, heads, s, s] score matrix — that memory saving is what
lets long-context configs run without the reference's recompute tricks.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["causal_attention", "NEG_INF"]

NEG_INF = -1e9  # large-but-finite; -inf breaks softmax when a row is all-masked


def _reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    attn_mask: Optional[jax.Array],
    dropout_rate: float,
    dropout_rng: Optional[jax.Array],
    deterministic: bool,
) -> jax.Array:
    """Plain XLA attention. Shapes: q,k,v [batch, seq, heads, head_dim]
    (kv seq may differ from q seq for cached decode)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))
    # [b, h, sq, sk]; accumulate scores in fp32 for softmax stability.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        # offset aligns the last q position with the last k position so the
        # same code serves full-sequence and incremental-decode calls.
        q_pos = jnp.arange(sq)[:, None] + (sk - sq)
        k_pos = jnp.arange(sk)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    if attn_mask is not None:
        # mask: 1 = attend, 0 = hide; broadcastable to [b, h, sq, sk]
        scores = jnp.where(attn_mask.astype(bool), scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    attn_mask: Optional[jax.Array] = None,
    kv_lens: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    use_flash: bool = True,
    mesh_shard: bool = True,
) -> jax.Array:
    """Multi-head scaled-dot-product attention, [b, s, h, d] layout.

    Routes to the Pallas flash kernel when profitable (TPU, train-time
    shapes, mask expressible as causal and/or right-padding ``kv_lens``);
    falls back to the XLA path for arbitrary ``attn_mask`` tensors or
    decode shapes. Attention dropout runs inside the kernel (hardware PRNG
    on real TPUs, counter-hash on the interpreter — see
    fleetx_tpu/ops/pallas/flash_attention.py), so dropout>0 training
    configs stay on the flash path. Both paths produce identical
    math in the deterministic case (kernel is tested against this
    reference implementation). Non-causal + kv_lens covers the ERNIE-style
    bidirectional encoder with right-padded batches.

    ``mesh_shard=False`` disables the kernel's mesh shard_map wrapper —
    required on the pp>1 path where attention runs under the pipeline's
    stage vmap (see flash_attention's docstring).
    """
    effective_dropout = 0.0 if deterministic else dropout_rate

    def _tileable(s: int) -> bool:
        # mirror flash_attention's block fitting: blocks shrink to the
        # largest divisor of the sequence. Route to the kernel only when a
        # reasonably-sized tile fits — a sequence like 1016 = 8*127 only
        # admits 8-row tiles, where per-grid-step overhead makes the
        # kernel slower than the XLA path it would replace.
        from fleetx_tpu.ops.pallas.flash_attention import (
            DEFAULT_BLOCK_K,
            DEFAULT_BLOCK_Q,
            fit_blocks,
        )

        bq, bk = fit_blocks(s, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
        # bq == s: the whole sequence is one tile (short seqs) — no grid
        # overhead regardless of size
        return bq is not None and (bq >= 128 or bq == s)

    def _pad_to_tileable(s: int):
        """Smallest padded length with a kernel-worthy tile, or None.
        Padded KEYS are masked via kv_lens; padded QUERY rows are computed
        and sliced off (their cotangent is zero, so gradients are exact).
        Fixes e.g. ViT's 197 (-> 200, one tile) and 1016 (-> 1024, 512
        tiles) instead of falling back to the XLA path."""
        for s_pad in range(s + (-s % 8), s + 129, 8):
            if _tileable(s_pad):
                return s_pad
        return None

    import os as _os

    def _unwrapped_under_tp() -> bool:
        # mesh_shard=False (the pp stage-vmap path) with an ambient mp>1
        # mesh: the bare Pallas call would make GSPMD replicate the
        # heads-sharded q/k/v — strictly worse than the XLA attention it
        # replaces, which GSPMD shards natively. Prefer the XLA path.
        if mesh_shard:
            return False
        from fleetx_tpu.parallel.mesh import ambient_mesh

        mesh = ambient_mesh()
        return mesh is not None and dict(mesh.shape).get("mp", 1) > 1

    s = q.shape[1]
    s_pad = s if _tileable(s) else _pad_to_tileable(s)
    can_flash = (
        use_flash
        and attn_mask is None
        and (effective_dropout == 0.0 or dropout_rng is not None)
        and q.shape[1] == k.shape[1]  # not incremental decode
        and s_pad is not None
        and not _unwrapped_under_tp()
        and (
            jax.default_backend() in ("tpu", "axon")
            # interpreter-mode kernel on CPU: the multichip dryrun uses this
            # to execute the flash shard_map composition on the virtual mesh
            or _os.environ.get("FLEETX_FORCE_FLASH") == "1"
        )
    )
    if can_flash:
        from fleetx_tpu.ops.pallas.flash_attention import flash_attention

        if s_pad != s:
            pad = ((0, 0), (0, s_pad - s), (0, 0), (0, 0))
            if kv_lens is None:
                kv_lens = jnp.full((q.shape[0],), s, jnp.int32)
            q, k, v = (jnp.pad(t, pad) for t in (q, k, v))
        out = flash_attention(
            q, k, v, causal=causal, kv_lens=kv_lens,
            dropout_rate=effective_dropout, dropout_rng=dropout_rng,
            mesh_shard=mesh_shard,
        )
        return out[:, :s] if s_pad != s else out
    if kv_lens is not None:
        key_valid = (
            jnp.arange(k.shape[1])[None, :] < kv_lens[:, None]
        )[:, None, None, :]  # [b, 1, 1, sk]
        attn_mask = (
            key_valid if attn_mask is None
            else attn_mask.astype(bool) & key_valid
        )
    return _reference_attention(
        q,
        k,
        v,
        causal=causal,
        attn_mask=attn_mask,
        dropout_rate=dropout_rate,
        dropout_rng=dropout_rng,
        deterministic=deterministic,
    )
