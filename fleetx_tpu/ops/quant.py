"""Quantization ops: int8 weight-only PTQ for serving + fake-quant QAT.

The reference's quantization story is paddleslim QAT configs
(qat_gpt_*.yaml; utils/export.py quant-aware export path). TPU-native
equivalents:

- **PTQ (serving)**: per-channel absmax int8 of dense kernels — halves (vs
  bf16) or quarters (vs fp32) the HBM a served model needs; matmuls
  dequantize on the fly (XLA fuses the scale multiply into the consumer).
- **QAT (training)**: straight-through-estimator fake quantization applied
  to weights inside the jitted loss; gradients flow as identity
  (lax.stop_gradient trick), matching paddleslim's weight-quant QAT
  semantics without graph surgery.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = [
    "quantize_int8",
    "dequantize_int8",
    "fake_quant",
    "quantize_tree_int8",
    "dequantize_tree_int8",
    "fake_quant_tree",
]


def quantize_int8(w: jax.Array, axis: int = -1):
    """(int8 values, fp32 scales) with per-channel absmax along ``axis``
    kept; scale shape broadcasts back against w."""
    w = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-12)), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_int8(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of quantize_int8: int8 values x per-channel scales -> float."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def fake_quant(w: jax.Array, bits: int = 8, axis: int = -1):
    """Quantize-dequantize with a straight-through gradient."""
    maxq = 2 ** (bits - 1) - 1
    w32 = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w32.ndim) if i != (axis % w32.ndim))
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax / maxq, 1e-12)
    deq = jnp.clip(jnp.round(w32 / scale), -maxq, maxq) * scale
    # STE: forward = deq, backward = identity
    return (w32 + jax.lax.stop_gradient(deq - w32)).astype(w.dtype)


def fake_quant_act(x: jax.Array, bits: int = 8) -> jax.Array:
    """Activation fake-quant: per-tensor DYNAMIC absmax (paddleslim
    ``abs_max`` activation observer), straight-through gradient.

    Per-tensor (not per-channel) matches quantized-serving kernels, which
    need one scale per activation tensor; dynamic (recomputed each step
    from the live tensor) is the jit-native form — no observer state
    threaded through the train step. The reference default
    ``moving_average_abs_max`` exists to accumulate *static serving
    scales*; our int8 export is weight-only (activations stay float at
    serving), so training-time dynamic scales carry the same QAT signal
    without the EMA state."""
    maxq = 2 ** (bits - 1) - 1
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32))
    scale = jnp.maximum(absmax / maxq, 1e-12)
    deq = jnp.clip(jnp.round(x32 / scale), -maxq, maxq) * scale
    return (x32 + jax.lax.stop_gradient(deq - x32)).astype(x.dtype)


def _is_weight(path, leaf) -> bool:
    """Dense/conv kernels only: >=2-D and named kernel/embedding-ish."""
    if not hasattr(leaf, "ndim") or leaf.ndim < 2:
        return False
    names = [str(getattr(k, "key", k)) for k in path]
    return any("kernel" in n or "embedding" in n.lower() for n in names)


def quantize_tree_int8(params) -> Any:
    """PTQ a param pytree: each eligible weight becomes
    {"_q8": int8, "_scale": fp32}; everything else passes through."""
    def one(path, leaf):
        if not _is_weight(path, leaf):
            return leaf
        q, s = quantize_int8(leaf)
        return {"_q8": q, "_scale": s}

    return jax.tree_util.tree_map_with_path(one, params)


def dequantize_tree_int8(tree, dtype=jnp.float32):
    """Inverse of quantize_tree_int8 (leaves the original dtype choice to
    the caller — serving usually wants bf16)."""
    def is_q(x):
        return isinstance(x, dict) and set(x) == {"_q8", "_scale"}

    return jax.tree.map(
        lambda x: dequantize_int8(x["_q8"], x["_scale"], dtype) if is_q(x) else x,
        tree,
        is_leaf=is_q,
    )


def fake_quant_tree(params, bits: int = 8):
    """QAT: fake-quantize every eligible weight in a param tree."""
    return jax.tree_util.tree_map_with_path(
        lambda p, l: fake_quant(l, bits) if _is_weight(p, l) else l, params
    )
