"""Quantization ops: int8 weight-only PTQ for serving + fake-quant QAT.

The reference's quantization story is paddleslim QAT configs
(qat_gpt_*.yaml; utils/export.py quant-aware export path). TPU-native
equivalents:

- **PTQ (serving)**: per-channel absmax int8 of dense kernels — halves (vs
  bf16) or quarters (vs fp32) the HBM a served model needs; matmuls
  dequantize on the fly (XLA fuses the scale multiply into the consumer).
- **QAT (training)**: straight-through-estimator fake quantization applied
  to weights inside the jitted loss; gradients flow as identity
  (lax.stop_gradient trick), matching paddleslim's weight-quant QAT
  semantics without graph surgery.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = [
    "QUANT_PREFIX_BUDGET",
    "common_prefix_len",
    "quant_parity_frac",
    "quantize_int8",
    "dequantize_int8",
    "quantize_kv",
    "dequantize_kv",
    "fake_quant",
    "fake_quant_act",
    "quantize_tree_int8",
    "dequantize_tree_int8",
    "fake_quant_tree",
    "resolve_serving_dtype",
    "serving_weight_params",
]

# The repo-wide tolerance budget for quantized serving configs
# (docs/QUANTIZATION.md "Tolerance contract"): a quantized greedy stream
# may diverge from the bf16 reference only in its trailing this-fraction
# of tokens (greedy decode is chaotic after a first argmax flip, so the
# longest common prefix is the meaningful measure). Consumed by
# tests/serving_parity.py (QUANT_ATOL) and the tools/bench_serving.py
# int8 record — ONE number, change it here with hardware evidence.
QUANT_PREFIX_BUDGET = 0.25


def resolve_serving_dtype(value, env_var, label=None) -> str:
    """Resolve a serving precision knob to ``"bf16"`` | ``"int8"``:
    explicit ``value`` wins, else ``env_var``, else bf16; anything else
    raises. The ONE parser behind ``FLEETX_SERVING_KV_DTYPE`` /
    ``FLEETX_SERVING_WEIGHT_DTYPE`` and the eval CLI's
    ``Offline_Eval.weight_dtype`` — adding a format (fp8) lands in every
    consumer at once."""
    import os

    out = str(value or (os.environ.get(env_var) if env_var else "")
              or "bf16").lower()
    if out not in ("bf16", "int8"):
        raise ValueError(
            f"{label or env_var} must be bf16|int8, got {out!r}")
    return out


def serving_weight_params(params, weight_dtype: str):
    """Apply the serving weight-only PTQ: at ``"int8"`` the tree becomes
    int8 + per-channel scales (idempotent — pre-quantized artifacts pass
    through). At ``"bf16"`` a float tree passes through untouched, but a
    tree that already carries ``{"_q8", "_scale"}`` leaves RAISES — the
    bf16 path has no dequant seam, so serving it would crash deep inside
    the first traced ``model.apply`` instead of here with a cause."""
    if weight_dtype == "int8":
        return quantize_tree_int8(params)
    if any(_is_qdict(leaf)
           for leaf in jax.tree.leaves(params, is_leaf=_is_qdict)):
        raise ValueError(
            "params are already int8-quantized ({'_q8', '_scale'} leaves) "
            f"but weight_dtype is {weight_dtype!r} — serve them with "
            "weight_dtype='int8' (the in-jit dequant seam) or expand them "
            "with dequantize_tree_int8 first")
    return params


def common_prefix_len(got, want) -> int:
    """Length of the longest common leading run of two token streams —
    where a quantized greedy stream diverged from its reference (the
    ``QUANT_PREFIX_BUDGET`` contract's measure)."""
    import numpy as np

    got, want = np.asarray(got), np.asarray(want)
    n = min(len(got), len(want))
    neq = np.nonzero(got[:n] != want[:n])[0]
    return int(neq[0]) if len(neq) else n


def quant_parity_frac(got, want) -> float:
    """THE contract measure for a quantized stream vs its reference: 0.0
    on a length mismatch (the budget tolerates diverging tails, not
    missing tokens — a truncated stream fails outright), otherwise
    common-prefix length over the reference length. A stream passes when
    this is >= ``1 - QUANT_PREFIX_BUDGET``. Shared by the test harness
    (tests/serving_parity.py) and the bench gate so they cannot drift."""
    if len(got) != len(want):
        return 0.0
    return common_prefix_len(got, want) / max(len(want), 1)


def quantize_int8(w: jax.Array, axis: int = -1):
    """(int8 values, fp32 scales) with per-channel absmax along ``axis``
    kept; scale shape broadcasts back against w."""
    w = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-12)), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_int8(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of quantize_int8: int8 values x per-channel scales -> float."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def quantize_kv(x: jax.Array):
    """Per-vector int8 for KV caches: absmax over the trailing (head_dim)
    axis, one fp32 scale per cached (row, head) vector.

    Returns ``(int8 values, fp32 scales [..., 1])`` — the keepdims
    trailing 1 is load-bearing: scale leaves then share the K/V leaves'
    ``[..., batch, cache_len, heads, X]`` suffix, so every tree walker
    that addresses K/V by trailing rank (``serving.scatter_slot``, the
    paged page scatter, block-spec index maps) handles scales unchanged.
    Per-vector granularity is what the dequant-in-kernel flash-decode
    variant streams: one scale multiply per K/V row next to the dot
    product (ops/pallas/decode_attention.py)."""
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(x32 / jnp.maximum(scale, 1e-12)), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv` — THE dequant the dense/XLA decode
    fallback shares with the flash kernels, so every attention path
    (prefill, custom masks, meshes, interpret) sees identical values.
    Same math as :func:`dequantize_int8`; the distinct name marks the KV
    contract (per-vector scales, [..., 1] layout) at call sites."""
    return dequantize_int8(q, scale, dtype)


def fake_quant(w: jax.Array, bits: int = 8, axis: int = -1):
    """Quantize-dequantize with a straight-through gradient."""
    maxq = 2 ** (bits - 1) - 1
    w32 = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w32.ndim) if i != (axis % w32.ndim))
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax / maxq, 1e-12)
    deq = jnp.clip(jnp.round(w32 / scale), -maxq, maxq) * scale
    # STE: forward = deq, backward = identity
    return (w32 + jax.lax.stop_gradient(deq - w32)).astype(w.dtype)


def fake_quant_act(x: jax.Array, bits: int = 8) -> jax.Array:
    """Activation fake-quant: per-tensor DYNAMIC absmax (paddleslim
    ``abs_max`` activation observer), straight-through gradient.

    Per-tensor (not per-channel) matches quantized-serving kernels, which
    need one scale per activation tensor; dynamic (recomputed each step
    from the live tensor) is the jit-native form — no observer state
    threaded through the train step. The reference default
    ``moving_average_abs_max`` exists to accumulate *static serving
    scales*; our int8 export is weight-only (activations stay float at
    serving), so training-time dynamic scales carry the same QAT signal
    without the EMA state."""
    maxq = 2 ** (bits - 1) - 1
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32))
    scale = jnp.maximum(absmax / maxq, 1e-12)
    deq = jnp.clip(jnp.round(x32 / scale), -maxq, maxq) * scale
    return (x32 + jax.lax.stop_gradient(deq - x32)).astype(x.dtype)


def _is_weight(path, leaf) -> bool:
    """Dense/conv kernels only: >=2-D and named kernel/embedding-ish."""
    if not hasattr(leaf, "ndim") or leaf.ndim < 2:
        return False
    names = [str(getattr(k, "key", k)) for k in path]
    return any("kernel" in n or "embedding" in n.lower() for n in names)


def _is_qdict(x) -> bool:
    """An already-quantized {"_q8", "_scale"} leaf pair."""
    return isinstance(x, dict) and set(x) == {"_q8", "_scale"}


def quantize_tree_int8(params) -> Any:
    """PTQ a param pytree: each eligible weight becomes
    {"_q8": int8, "_scale": fp32}; everything else passes through.
    Idempotent: already-quantized subtrees pass through untouched, so a
    ServingEngine handed an InferenceEngine's pre-quantized params does
    not double-quantize."""
    def one(path, leaf):
        if _is_qdict(leaf) or not _is_weight(path, leaf):
            return leaf
        q, s = quantize_int8(leaf)
        return {"_q8": q, "_scale": s}

    return jax.tree_util.tree_map_with_path(one, params, is_leaf=_is_qdict)


def dequantize_tree_int8(tree, dtype=jnp.float32):
    """Inverse of quantize_tree_int8 (leaves the original dtype choice to
    the caller — serving usually wants bf16)."""
    return jax.tree.map(
        lambda x: (dequantize_int8(x["_q8"], x["_scale"], dtype)
                   if _is_qdict(x) else x),
        tree,
        is_leaf=_is_qdict,
    )


def fake_quant_tree(params, bits: int = 8):
    """QAT: fake-quantize every eligible weight in a param tree."""
    return jax.tree_util.tree_map_with_path(
        lambda p, l: fake_quant(l, bits) if _is_weight(p, l) else l, params
    )
