#!/usr/bin/env bash
# Imagen 397M base stage pretraining (reference projects/imagen/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/multimodal/imagen/imagen_397M_text2im_64x64.yaml "$@"
