#!/usr/bin/env bash
# Imagen SR-256 stage pretraining (reference projects/imagen/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/multimodal/imagen/imagen_super_resolution_256.yaml "$@"
