#!/usr/bin/env bash
# GPT-345M batch generation over dp8 (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tasks/gpt/generation.py -c configs/nlp/gpt/generation_gpt_345M_dp8.yaml "$@"
