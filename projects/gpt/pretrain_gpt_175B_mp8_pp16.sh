#!/usr/bin/env bash
# GPT-175B: tp8 x pp16 (+ sequence parallel) over 128 chips.
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/pretrain_gpt_175B_mp8_pp16.yaml "$@"
