#!/usr/bin/env bash
# GPT-345M quantization-aware training over mp8 (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/qat_gpt_345M_mp8.yaml "$@"
