#!/usr/bin/env bash
# Auto-parallel GPT-1.3B dp8 (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/auto/pretrain_gpt_1.3B_dp8.yaml "$@"
