#!/usr/bin/env bash
# GPT-1.3B data-parallel over 8 chips. On a TPU pod slice every host runs
# the same command (jax.distributed discovers peers); no launcher needed.
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/pretrain_gpt_1.3B_dp8.yaml "$@"
