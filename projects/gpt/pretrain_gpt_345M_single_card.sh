#!/usr/bin/env bash
# GPT-345M single-chip pretraining (reference projects/gpt/pretrain_gpt_345M_single_card.sh)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/pretrain_gpt_345M_single_card.yaml "$@"
