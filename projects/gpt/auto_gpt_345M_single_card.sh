#!/usr/bin/env bash
# Auto-parallel GPT-345M (GSPMD is the one engine) (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/auto/pretrain_gpt_345M_single_card.yaml "$@"
