#!/usr/bin/env bash
# Serve an exported GPT-345M (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/inference.py -c configs/nlp/gpt/inference_gpt_345M_single_card.yaml "$@"
