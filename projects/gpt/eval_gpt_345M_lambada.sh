#!/usr/bin/env bash
# Offline LAMBADA accuracy eval (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/eval.py -c configs/nlp/gpt/eval_gpt_345M_single_card.yaml -o Offline_Eval.cloze_eval=True "$@"
