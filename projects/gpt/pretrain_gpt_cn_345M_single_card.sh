#!/usr/bin/env bash
# Chinese GPT-345M pretraining (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/pretrain_gpt_cn_345M_single_card.yaml "$@"
