#!/usr/bin/env bash
# GPT-1.3B single-chip pretraining (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/pretrain_gpt_1.3B_single_card.yaml "$@"
