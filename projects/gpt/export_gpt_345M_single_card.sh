#!/usr/bin/env bash
# Export GPT-345M to a serving artifact (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/export.py -c configs/nlp/gpt/pretrain_gpt_345M_single_card.yaml "$@"
