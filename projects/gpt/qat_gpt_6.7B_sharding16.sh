#!/usr/bin/env bash
# GPT-6.7B QAT with 16-way sharding (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/qat_gpt_6.7B_sharding16.yaml "$@"
