#!/usr/bin/env bash
# GPT-1.3B at 32k context via ring-attention context parallelism.
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/pretrain_gpt_1.3B_longcontext_cp8.yaml "$@"
