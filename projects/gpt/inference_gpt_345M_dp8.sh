#!/usr/bin/env bash
# Serve GPT-345M replicated over 8 chips (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/inference.py -c configs/nlp/gpt/inference_gpt_345M_dp8.yaml "$@"
