#!/usr/bin/env bash
# Auto-parallel GPT-6.7B sharding16 (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/auto/pretrain_gpt_6.7B_sharding16.yaml "$@"
