#!/usr/bin/env bash
# GPT-345M GLUE finetuning (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/finetune_gpt_345M_single_card_glue.yaml "$@"
