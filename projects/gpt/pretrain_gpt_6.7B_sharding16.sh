#!/usr/bin/env bash
# GPT-6.7B with ZeRO sharding over 16 chips (reference sharding16 recipe).
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/gpt/pretrain_gpt_6.7B_sharding16.yaml "$@"
