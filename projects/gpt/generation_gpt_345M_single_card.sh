#!/usr/bin/env bash
# GPT-345M text generation (reference projects/gpt/)
set -eux
cd "$(dirname "$0")/../.."
python tasks/gpt/generation.py -c configs/nlp/gpt/generation_gpt_345M_single_card.yaml "$@"
