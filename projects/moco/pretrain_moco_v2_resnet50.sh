#!/usr/bin/env bash
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/moco/moco_v2_resnet50.yaml "$@"
