#!/usr/bin/env bash
# Linear probe on a frozen MoCo backbone (reference projects/moco/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/moco/moco_lincls_in1k_1n8c.yaml "$@"
