#!/usr/bin/env bash
# MoCo v1 ImageNet pretraining (reference projects/moco/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/moco/mocov1_pt_in1k_1n8c.yaml "$@"
