#!/usr/bin/env bash
# MoE GPT-1.3B with expert parallelism over dp8 (reference projects/moe/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/moe/pretrain_moe_1.3B_dp8.yaml "$@"
