#!/usr/bin/env bash
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/ernie/pretrain_ernie_base.yaml "$@"
