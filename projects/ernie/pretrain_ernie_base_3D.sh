#!/usr/bin/env bash
# ERNIE base 3D hybrid parallel dp2xmp2xpp2 (reference projects/ernie/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/ernie/pretrain_ernie_base_3D.yaml "$@"
