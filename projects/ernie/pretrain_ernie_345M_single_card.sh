#!/usr/bin/env bash
# ERNIE-345M single-chip pretraining (reference projects/ernie/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/ernie/pretrain_ernie_base_345M_single_card.yaml "$@"
