#!/usr/bin/env bash
# ERNIE-6.7B 16-way sharding (reference projects/ernie/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/ernie/pretrain_ernie_base_6.7B_sharding16.yaml "$@"
