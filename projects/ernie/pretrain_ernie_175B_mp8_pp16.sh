#!/usr/bin/env bash
# ERNIE-175B-scale mp8xpp16 (reference projects/ernie/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/ernie/pretrain_ernie_base_175B_mp8_pp16.yaml "$@"
