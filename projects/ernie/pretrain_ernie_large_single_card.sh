#!/usr/bin/env bash
# ERNIE-large pretraining (reference projects/ernie/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/nlp/ernie/pretrain_ernie_large_single_card.yaml "$@"
