#!/usr/bin/env bash
# Folding trunk pretraining with DAP over 8 chips (reference projects/protein_folding/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/protein/pretrain_folding_dap8.yaml "$@"
