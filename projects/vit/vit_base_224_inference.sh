#!/usr/bin/env bash
# Serve an exported ViT-B/16 classifier (reference projects/vit/)
set -eux
cd "$(dirname "$0")/../.."
python tools/inference.py -c configs/vis/vit/ViT_base_patch16_224_inference.yaml "$@"
