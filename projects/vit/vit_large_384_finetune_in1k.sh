#!/usr/bin/env bash
# ViT-L/16-384 ImageNet finetune (reference projects/vit/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/vit/ViT_large_patch16_384_ft_in1k_2n16c_dp_fp16o2.yaml "$@"
