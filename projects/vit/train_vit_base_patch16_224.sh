#!/usr/bin/env bash
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/vit/vit_base_patch16_224.yaml "$@"
