#!/usr/bin/env bash
# ViT-tiny CI smoke on CIFAR-10 (reference projects/vit/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/vit/ViT_tiny_patch16_224_ci_cifar10_1n8c_dp_fp16o2.yaml "$@"
