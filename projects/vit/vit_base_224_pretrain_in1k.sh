#!/usr/bin/env bash
# ViT-B/16 ImageNet pretraining (16-way dp) (reference projects/vit/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/vit/ViT_base_patch16_224_pt_in1k_2n16c_dp_fp16o2.yaml "$@"
