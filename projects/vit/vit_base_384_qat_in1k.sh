#!/usr/bin/env bash
# ViT-B/16-384 QAT finetune (reference projects/vit/)
set -eux
cd "$(dirname "$0")/../.."
python tools/train.py -c configs/vis/vit/ViT_base_patch16_384_ft_qat_in1k_2n16c_dp_fp16o2.yaml "$@"
