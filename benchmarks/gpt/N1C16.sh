#!/usr/bin/env bash
# 16-device topology grid (reference test_tipc N4C32 analogue, lower rung).
# Default: 16-device virtual CPU mesh — a topology/convergence gate, not a
# perf number. On a real >=16-chip slice: BENCH_MATRIX_PLATFORM=tpu $0
cd "$(dirname "$0")/../.."
python tools/bench_matrix.py --devices 16 --out "${1:-bench_n1c16.json}"
