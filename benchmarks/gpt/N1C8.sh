#!/usr/bin/env bash
# 8-device topology grid (reference test_tipc N1C8 entries; virtual CPU
# mesh when no 8-chip TPU is attached).
cd "$(dirname "$0")/../.."
if ! python -c "import jax; assert jax.device_count() >= 8" 2>/dev/null; then
    export JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8"
fi
python tools/bench_matrix.py --devices 8 --out "${1:-bench_n1c8.json}"
