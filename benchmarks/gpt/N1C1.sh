#!/usr/bin/env bash
# Single-chip benchmark case (reference test_tipc N1C1 entry).
cd "$(dirname "$0")/../.."
python tools/bench_matrix.py --devices 1 --out "${1:-bench_n1c1.json}"
