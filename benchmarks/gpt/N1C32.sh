#!/usr/bin/env bash
# 32-device topology grid (reference test_tipc N4C32 entries: 4 hosts x 8
# cards; here one 32-device virtual mesh — same global topologies, ICI/DCN
# split left to GSPMD). DP2-MP2-PP2-Sharding4-Stage2 is the reference's
# flagship N4C32 hybrid case.
cd "$(dirname "$0")/../.."
# default: 32-device virtual CPU mesh (topology/convergence gate); on a
# real >=32-chip slice: BENCH_MATRIX_PLATFORM=tpu $0
python tools/bench_matrix.py --devices 32 --out "${1:-bench_n1c32.json}"
